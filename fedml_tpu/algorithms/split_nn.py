"""SplitNN — split learning across a client body and a server head.

Reference choreography (``fedml_api/distributed/split_nn/``): the model is
cut into a client lower half and a server upper half; every batch, the active
client sends activations + labels up (client.py:24-29), the server runs the
head, computes CE loss, backprops to the activation boundary and returns the
activation gradient (server.py:40-60); clients take turns being active,
advancing round-robin each epoch (server.py:70-71).  The process boundary is
crossed EVERY batch — the latency-critical path (SURVEY.md §3.3).

TPU-native inversion: on-chip, the "activation exchange" is just function
composition — ``head(body(x))`` differentiates end-to-end inside ONE jit
program, and XLA places the boundary; there is no wire, so the per-batch
round-trip cost collapses to zero.  The split is kept *architecturally* (two
parameter trees, two optimizers, the server never sees ``x`` and the client
never sees the loss internals) so the privacy/topology semantics match.  For
a true cross-silo wire, `SplitNNClientActor`/`SplitNNServerActor` run the
same two halves over the message layer with per-batch activation/grad
messages, exactly like the reference.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.comm.actors import ClientManager, ServerManager
from fedml_tpu.comm.message import Message

Pytree = Any

MSG_ACTS = "splitnn.acts"          # client -> server: activations + labels
MSG_GRADS = "splitnn.grads"        # server -> client: dL/dacts
MSG_DONE = "splitnn.done"


@dataclasses.dataclass
class SplitNNConfig:
    epochs_per_client: int = 1     # MAX_EPOCH_PER_NODE (client.py:16)
    rounds: int = 1                # full round-robin sweeps over clients
    client_lr: float = 0.1         # optim.SGD(lr=0.1, momentum=0.9, wd=5e-4)
    server_lr: float = 0.1
    momentum: float = 0.9
    weight_decay: float = 5e-4


def _sgd(lr, momentum, wd):
    return optax.chain(optax.add_decayed_weights(wd),
                       optax.sgd(lr, momentum=momentum))


class SplitModel:
    """The split pair: ``body`` (client half) maps x -> activations, ``head``
    (server half) maps activations -> logits."""

    def __init__(self, body, head):
        self.body = body
        self.head = head

    def init(self, rng: jax.Array, sample_x: jax.Array) -> Tuple[Pytree, Pytree]:
        rb, rh = jax.random.split(rng)
        body_params = self.body.init(rb, sample_x)["params"]
        acts = self.body.apply({"params": body_params}, sample_x)
        head_params = self.head.init(rh, acts)["params"]
        return body_params, head_params

    def forward_body(self, body_params, x):
        return self.body.apply({"params": body_params}, x)

    def forward_head(self, head_params, acts):
        return self.head.apply({"params": head_params}, acts)


class SplitNNSimulator:
    """On-chip split learning: one jit'd step trains both halves end-to-end;
    round-robin client activation matches server.py:70-71."""

    def __init__(self, split: SplitModel, cfg: SplitNNConfig):
        self.split = split
        self.cfg = cfg
        self.client_opt = _sgd(cfg.client_lr, cfg.momentum, cfg.weight_decay)
        self.server_opt = _sgd(cfg.server_lr, cfg.momentum, cfg.weight_decay)

        def loss_fn(body_params, head_params, batch):
            acts = self.split.forward_body(body_params, batch["x"])
            logits = self.split.forward_head(head_params, acts)
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits, batch["y"])
            m = batch["mask"]
            loss = jnp.sum(ce * m) / jnp.maximum(jnp.sum(m), 1.0)
            correct = jnp.sum((jnp.argmax(logits, -1) == batch["y"]) * m)
            return loss, correct

        def epoch_step(body_params, head_params, body_opt, head_opt, data):
            """One client's epoch: scan over its batches."""
            def step(carry, batch):
                bp, hp, bo, ho = carry
                (loss, correct), grads = jax.value_and_grad(
                    loss_fn, argnums=(0, 1), has_aux=True)(bp, hp, batch)
                gb, gh = grads
                ub, bo = self.client_opt.update(gb, bo, bp)
                uh, ho = self.server_opt.update(gh, ho, hp)
                return ((optax.apply_updates(bp, ub),
                         optax.apply_updates(hp, uh), bo, ho),
                        {"loss": loss, "correct": correct,
                         "total": jnp.sum(batch["mask"])})

            (bp, hp, bo, ho), ms = jax.lax.scan(
                step, (body_params, head_params, body_opt, head_opt), data)
            return bp, hp, bo, ho, ms

        self._epoch_step = jax.jit(epoch_step)
        self._eval_loss = jax.jit(loss_fn)

    def run(self, client_data: List[Dict[str, jnp.ndarray]],
            rng: jax.Array) -> Dict[str, Any]:
        """client_data: per-client {"x": [S, B, ...], "y": [S, B], "mask"}.
        Each client holds its own body params (the reference gives each
        client a copy it trains while active, passing it along the ring via
        the server; we model the canonical variant where the active client's
        trained body is handed to the next client, client.py:12-13
        node_left/node_right semantics)."""
        cfg = self.cfg
        sample_x = jax.tree.map(lambda v: v[0], client_data[0]["x"])
        body_params, head_params = self.split.init(rng, sample_x)
        body_opt = self.client_opt.init(body_params)
        head_opt = self.server_opt.init(head_params)
        history = []
        for sweep in range(cfg.rounds):
            for ci, data in enumerate(client_data):
                for _ in range(cfg.epochs_per_client):
                    body_params, head_params, body_opt, head_opt, ms = \
                        self._epoch_step(body_params, head_params,
                                         body_opt, head_opt, data)
                    history.append({
                        "sweep": sweep, "client": ci,
                        "loss": float(np.mean(np.asarray(ms["loss"]))),
                        "acc": float(np.sum(np.asarray(ms["correct"]))
                                     / max(1.0, float(np.sum(np.asarray(ms["total"])))))})
        return {"body_params": body_params, "head_params": head_params,
                "history": history}

    def evaluate(self, body_params, head_params,
                 data: Dict[str, jnp.ndarray]) -> Dict[str, float]:
        total_loss, total_correct, total = 0.0, 0.0, 0.0
        for s in range(data["x"].shape[0]):
            batch = {k: data[k][s] for k in ("x", "y", "mask")}
            loss, correct = self._eval_loss(body_params, head_params, batch)
            n = float(np.sum(np.asarray(batch["mask"])))
            total_loss += float(loss) * n
            total_correct += float(correct)
            total += n
        return {"loss": total_loss / max(total, 1.0),
                "acc": total_correct / max(total, 1.0)}


# ---------------------------------------------------------------------------
# Cross-silo wire variant: explicit per-batch activation/grad messages.

class SplitNNServerActor(ServerManager):
    """Holds the head; answers every MSG_ACTS with MSG_GRADS
    (server.py forward_pass/backward_pass)."""

    def __init__(self, node_id, transport, split: SplitModel,
                 head_params, cfg: SplitNNConfig):
        super().__init__(node_id, transport)
        self.split = split
        self.cfg = cfg
        self.head_params = head_params
        self.opt = _sgd(cfg.server_lr, cfg.momentum, cfg.weight_decay)
        self.opt_state = self.opt.init(head_params)
        self.metrics = {"correct": 0.0, "total": 0.0, "loss_sum": 0.0}

        def step(head_params, opt_state, acts, y, mask):
            def loss_fn(hp, a):
                logits = self.split.forward_head(hp, a)
                ce = optax.softmax_cross_entropy_with_integer_labels(logits, y)
                loss = jnp.sum(ce * mask) / jnp.maximum(jnp.sum(mask), 1.0)
                correct = jnp.sum((jnp.argmax(logits, -1) == y) * mask)
                return loss, correct

            (loss, correct), (g_hp, g_acts) = jax.value_and_grad(
                loss_fn, argnums=(0, 1), has_aux=True)(head_params, acts)
            updates, opt_state = self.opt.update(g_hp, opt_state, head_params)
            return (optax.apply_updates(head_params, updates), opt_state,
                    g_acts, loss, correct)

        self._step = jax.jit(step)

    def register_handlers(self):
        self.register_handler(MSG_ACTS, self._on_acts)
        self.register_handler(MSG_DONE, lambda m: self.finish())

    def _on_acts(self, msg: Message):
        acts = jnp.asarray(msg.get("acts"))
        y = jnp.asarray(msg.get("y"))
        mask = jnp.asarray(msg.get("mask"))
        self.head_params, self.opt_state, g_acts, loss, correct = self._step(
            self.head_params, self.opt_state, acts, y, mask)
        self.metrics["correct"] += float(correct)
        self.metrics["total"] += float(np.sum(np.asarray(mask)))
        self.metrics["loss_sum"] += float(loss) * float(np.sum(np.asarray(mask)))
        self.send(MSG_GRADS, msg.sender_id, grads=np.asarray(g_acts))


class SplitNNClientActor(ClientManager):
    """Holds the body; streams its batches, applying returned grads
    (client.py forward_pass/backward_pass)."""

    def __init__(self, node_id, transport, split: SplitModel, body_params,
                 data: Dict[str, np.ndarray], server_id: int,
                 cfg: SplitNNConfig):
        super().__init__(node_id, transport)
        self.split = split
        self.cfg = cfg
        self.body_params = body_params
        self.data = data
        self.server_id = server_id
        self.opt = _sgd(cfg.client_lr, cfg.momentum, cfg.weight_decay)
        self.opt_state = self.opt.init(body_params)
        self._batch_idx = 0
        self._epoch = 0

        def fwd(body_params, x):
            return self.split.forward_body(body_params, x)

        def bwd(body_params, opt_state, x, g_acts):
            _, vjp = jax.vjp(lambda bp: fwd(bp, x), body_params)
            (g_bp,) = vjp(g_acts)
            updates, opt_state = self.opt.update(g_bp, opt_state, body_params)
            return optax.apply_updates(body_params, updates), opt_state

        self._fwd = jax.jit(fwd)
        self._bwd = jax.jit(bwd)

    def register_handlers(self):
        self.register_handler(MSG_GRADS, self._on_grads)

    def start_epoch(self):
        self._batch_idx = 0
        self._send_next_batch()

    def _current_batch(self):
        return {k: jnp.asarray(self.data[k][self._batch_idx])
                for k in ("x", "y", "mask")}

    def _send_next_batch(self):
        b = self._current_batch()
        self._last_x = b["x"]
        acts = self._fwd(self.body_params, b["x"])
        self.send(MSG_ACTS, self.server_id, acts=np.asarray(acts),
                  y=np.asarray(b["y"]), mask=np.asarray(b["mask"]))

    def _on_grads(self, msg: Message):
        g_acts = jnp.asarray(msg.get("grads"))
        self.body_params, self.opt_state = self._bwd(
            self.body_params, self.opt_state, self._last_x, g_acts)
        self._batch_idx += 1
        if self._batch_idx < self.data["x"].shape[0]:
            self._send_next_batch()
        else:
            self._epoch += 1
            if self._epoch < self.cfg.epochs_per_client:
                self.start_epoch()
            else:
                self.send(MSG_DONE, self.server_id)
