"""Switch-style mixture-of-experts FFN — the expert-parallel (ep) member
of the parallelism family.

The reference has no MoE capability (its NLP zoo stops at LSTMs,
fedml_api/model/nlp/rnn.py); this layer exists because expert parallelism
is a first-class sharding for the framework (alongside dp/tp/sp/pp): the
expert tables carry an explicit leading ``[E, ...]`` axis and all routing
is dense einsums over it, so GSPMD shards experts across an ``experts``
mesh axis with no manual collectives (parallel/expert.py) — the
all-to-all dispatch/combine falls out of the einsum shardings, the
scaling-book way.

Routing follows Fedus et al. 2021 (Switch Transformer): top-1 router,
capacity-bounded dispatch (tokens over capacity are DROPPED and ride the
residual connection), and the load-balancing auxiliary loss
``E * Σ_e f_e·P_e`` sown into the ``losses`` collection (NWPWorkload adds
it to the CE loss when the model carries experts; ``sow`` is a silent
no-op under plain apply, so eval paths need no changes).

Everything is static-shaped and scan/vmap-friendly: argmax + cumsum +
one_hot + einsum — no sorting, no dynamic shapes, nothing that blocks the
MXU (SURVEY.md "XLA semantics").
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp


class SwitchFFN(nn.Module):
    """Top-1 MoE FFN: [B, T, D] -> [B, T, D] with E experts.

    ``capacity_factor`` bounds each expert's token buffer at
    ``ceil(cf * N / E)`` (N = B*T tokens): static shapes for XLA, graceful
    drop for hot experts.  The router always runs f32 (softmax is
    range-sensitive; matches the workloads' f32-loss convention)."""
    n_experts: int
    d_model: int
    d_ff: int
    capacity_factor: float = 1.25
    dtype: object = None

    @nn.compact
    def __call__(self, x):
        b, t, d = x.shape
        n_tok = b * t
        e = self.n_experts
        cap = max(1, int(-(-self.capacity_factor * n_tok // e)))
        xt = x.reshape(n_tok, d)

        # -- top-1 routing (f32) ------------------------------------------
        router_logits = nn.Dense(e, dtype=jnp.float32, name="router")(
            xt.astype(jnp.float32))
        probs = jax.nn.softmax(router_logits, axis=-1)          # [N, E]
        expert = jnp.argmax(probs, axis=-1)                     # [N]
        gate = jnp.max(probs, axis=-1)                          # [N]
        oh = jax.nn.one_hot(expert, e, dtype=jnp.float32)       # [N, E]

        # load-balance aux (Switch eq. 4): pushes f (dispatch fraction)
        # and P (mean router prob) toward uniform
        f_frac = jnp.mean(oh, axis=0)
        p_mean = jnp.mean(probs, axis=0)
        self.sow("losses", "load_balance", e * jnp.sum(f_frac * p_mean))

        # -- capacity-bounded dispatch tensor [N, E, C] --------------------
        # position of each token within its expert's buffer; one_hot of an
        # out-of-range position is all-zero, which IS the token drop
        pos = jnp.cumsum(oh, axis=0) - 1.0
        pos_in_e = jnp.sum(pos * oh, axis=-1).astype(jnp.int32)  # [N]
        disp = oh[:, :, None] * jax.nn.one_hot(
            pos_in_e, cap, dtype=jnp.float32)[:, None, :]       # [N, E, C]

        # -- expert FFN over the explicit [E, ...] tables ------------------
        dt = self.dtype or x.dtype
        w1 = self.param("w1", nn.initializers.lecun_normal(),
                        (e, d, self.d_ff), jnp.float32)
        b1 = self.param("b1", nn.initializers.zeros, (e, self.d_ff),
                        jnp.float32)
        w2 = self.param("w2", nn.initializers.lecun_normal(),
                        (e, self.d_ff, d), jnp.float32)
        b2 = self.param("b2", nn.initializers.zeros, (e, d), jnp.float32)

        xe = jnp.einsum("nec,nd->ecd", disp.astype(dt), xt.astype(dt))
        h = jnp.einsum("ecd,edf->ecf", xe, w1.astype(dt)) \
            + b1.astype(dt)[:, None, :]
        h = nn.gelu(h)
        ye = jnp.einsum("ecf,efd->ecd", h, w2.astype(dt)) \
            + b2.astype(dt)[:, None, :]

        # -- combine (gate-weighted; dropped tokens come back as 0) --------
        comb = (disp * gate[:, None, None]).astype(dt)
        yt = jnp.einsum("nec,ecd->nd", comb, ye)
        return yt.reshape(b, t, d).astype(x.dtype)
