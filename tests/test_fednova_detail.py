"""FedNova edge semantics: padding invariance with momentum, mesh parity."""

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.fednova import (
    FedNova, FedNovaConfig, make_fednova_local_trainer,
)
from fedml_tpu.data.stacking import stack_client_data, FederatedData
from fedml_tpu.models import LogisticRegression
from fedml_tpu.trainer.workload import ClassificationWorkload


def _workload():
    return ClassificationWorkload(LogisticRegression(6, 3), num_classes=3,
                                  grad_clip_norm=None)


def test_padded_batches_do_not_pollute_momentum():
    """A client whose data occupies 2 of 4 stacked batches must train exactly
    like the same data stacked into 2 batches — momentum buffer, cum_grad and
    a_i all frozen across padded steps (incl. weight decay)."""
    wl = _workload()
    rng = np.random.RandomState(0)
    x = rng.randn(8, 6).astype(np.float32)
    y = rng.randint(0, 3, 8).astype(np.int32)
    cfg = FedNovaConfig(epochs=3, lr=0.1, momentum=0.9, wd=0.01, mu=0.05)
    train = make_fednova_local_trainer(wl, cfg)

    tight = stack_client_data([x], [y], batch_size=4)           # 2 batches
    loose = stack_client_data([x, np.repeat(x, 2, 0)], [y, np.repeat(y, 2, 0)],
                              batch_size=4)                      # 4 batches
    params = wl.init(jax.random.key(0),
                     jax.tree.map(lambda v: jnp.asarray(v[0, 0]),
                                  {k: tight[k] for k in ("x", "y", "mask")}))
    r = jax.random.key(1)
    p_tight, aux_tight = train(
        params, {k: jnp.asarray(tight[k][0]) for k in ("x", "y", "mask")}, r)
    p_loose, aux_loose = train(
        params, {k: jnp.asarray(loose[k][0]) for k in ("x", "y", "mask")}, r)

    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5,
                                                         atol=1e-6),
                 p_tight, p_loose)
    np.testing.assert_allclose(aux_tight["a_i"], aux_loose["a_i"], rtol=1e-6)
    np.testing.assert_allclose(aux_tight["local_steps"],
                               aux_loose["local_steps"], rtol=1e-6)


def test_fednova_mesh_equals_single_chip(devices):
    from fedml_tpu.parallel.mesh import make_mesh
    wl = _workload()
    rng = np.random.RandomState(1)
    xs = [rng.randn(rng.randint(6, 15), 6).astype(np.float32) for _ in range(8)]
    ys = [rng.randint(0, 3, len(x)).astype(np.int32) for x in xs]
    train = stack_client_data(xs, ys, batch_size=4)
    data = FederatedData(client_num=8, class_num=3, train=train, test=train)

    cfg = FedNovaConfig(comm_round=3, client_num_per_round=8, epochs=2,
                        batch_size=4, lr=0.1, momentum=0.9, gmf=0.5,
                        frequency_of_the_test=100)
    single = FedNova(wl, data, cfg)
    mesh = make_mesh(devices=devices, client_axis=8, model_axis=1)
    sharded = FedNova(wl, data, cfg, mesh=mesh)

    p0 = single.init_params(jax.random.key(2))
    ps = single.run(params=jax.tree.map(jnp.copy, p0), rng=jax.random.key(3))
    pm = sharded.run(params=jax.tree.map(jnp.copy, p0), rng=jax.random.key(3))
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4,
                                                         atol=1e-5), ps, pm)
