"""fedml_tpu — a TPU-native federated learning framework.

A ground-up JAX/XLA re-design with the capabilities of the reference FedML
library (PyTorch + MPI message passing).  The core inversion: on-TPU,
"communication" is an XLA collective inside one jit-compiled program — a
FedAvg round that in the reference is a choreography of MPI messages
(`fedml_api/distributed/fedavg/FedAvgServerManager.py`) collapses here into a
single `shard_map`-ped cohort step whose aggregation is a weighted `lax.psum`
over the ICI mesh.  The message-passing actor layer survives only at the
cross-silo / host edge (gRPC/MQTT transports in `fedml_tpu.comm`).

Layer map (mirrors SURVEY.md §1 of the reference):

    fedml_tpu.experiments   CLI entry points (parity with fedml_experiments/)
    fedml_tpu.algorithms    FedAvg/FedOpt/FedProx/FedNova/... (fedml_api/*)
    fedml_tpu.models        flax model zoo (fedml_api/model/*)
    fedml_tpu.data          dataset loaders + cohort stacking (data_preprocessing/*)
    fedml_tpu.core          kernel: aggregation math, sampling, partition,
                            robustness, topology (fedml_core/*)
    fedml_tpu.parallel      mesh / shard_map cohort engine (replaces MPI runtime)
    fedml_tpu.comm          cross-silo transports: Message protocol, local fake,
                            gRPC, MQTT (fedml_core/distributed/communication/*)
    fedml_tpu.obs           observability: distributed round tracing,
                            telemetry registry (Prometheus/JSON), run reports
                            (beyond the reference's rank-0 wandb logging)
"""

__version__ = "0.1.0"

# Curated top-level API, resolved lazily: `import fedml_tpu` stays
# instant (no jax/flax import at package import time — the CLI and tests
# rely on picking the platform BEFORE anything queries devices), while
# `fedml_tpu.FedAvg` etc. work as a library user expects.
_API = {
    "FedAvg": "fedml_tpu.algorithms",
    "FedAvgConfig": "fedml_tpu.algorithms",
    "load_data": "fedml_tpu.data",
    "make_mesh": "fedml_tpu.parallel.mesh",
    "make_cohort_step": "fedml_tpu.parallel.cohort",
    "ClassificationWorkload": "fedml_tpu.trainer.workload",
    "NWPWorkload": "fedml_tpu.trainer.workload",
    "make_client_optimizer": "fedml_tpu.trainer.workload",
    "make_local_trainer": "fedml_tpu.trainer.local_sgd",
    "RoundCheckpointer": "fedml_tpu.utils.checkpoint",
    "MetricsSink": "fedml_tpu.utils.metrics",
    "SpanTracer": "fedml_tpu.obs.trace",
    "TelemetryRegistry": "fedml_tpu.obs.telemetry",
}

__all__ = sorted(_API) + ["__version__"]


def __getattr__(name: str):
    if name in _API:
        import importlib
        return getattr(importlib.import_module(_API[name]), name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_API))
