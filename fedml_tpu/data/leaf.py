"""LEAF-format federated datasets (MNIST, shakespeare, synthetic, FEMNIST-leaf).

The LEAF layout (``fedml_api/data_preprocessing/MNIST/data_loader.py:8-47``):
train/ and test/ directories of ``.json`` files, each with keys ``users``,
``user_data`` ({user: {"x": [...], "y": [...]}}) and optionally
``hierarchies``/``num_samples``.  The reference shuffles each client's samples
with a fixed seed of 100 (MNIST/data_loader.py:57-63) — we reproduce that via
``shuffle_seed=100`` in the stacker so accuracy trajectories are comparable.

TPU-native difference: instead of per-client torch DataLoaders we stack all
clients into padded ``[C, S, B, ...]`` host arrays once (SURVEY.md §2.4) and
gather cohorts per round.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .stacking import FederatedData, stack_client_data, batch_global
from .text import CharVocab, SHAKESPEARE_SEQ_LEN

MNIST_SHUFFLE_SEED = 100  # MNIST/data_loader.py:58


def read_leaf_dirs(train_dir: str, test_dir: str
                   ) -> Tuple[List[str], List[str], Dict, Dict]:
    """Parse LEAF train/test json directories -> (users, groups, train, test)
    (MNIST/data_loader.py:8-47). Users are sorted for determinism."""
    def read_dir(d):
        users, groups, data = [], [], {}
        for f in sorted(os.listdir(d)):
            if not f.endswith(".json"):
                continue
            with open(os.path.join(d, f)) as inf:
                cdata = json.load(inf)
            users.extend(cdata["users"])
            groups.extend(cdata.get("hierarchies", []))
            data.update(cdata["user_data"])
        return users, groups, data

    train_users, groups, train_data = read_dir(train_dir)
    _, _, test_data = read_dir(test_dir)
    return sorted(train_users), groups, train_data, test_data


def _stack_leaf(users: Sequence[str], train_data: Dict, test_data: Dict,
                batch_size: int, class_num: int,
                encode: Optional[Callable] = None,
                x_dtype=np.float32, y_dtype=np.int32) -> FederatedData:
    """Common LEAF -> FederatedData path. ``encode`` maps one client's raw
    (x list, y list) to (x array, y array)."""
    def prep(data, u):
        ux, uy = data.get(u, {"x": [], "y": []}), None
        x, y = ux["x"], ux["y"]
        if encode is not None:
            return encode(x, y)
        return (np.asarray(x, dtype=x_dtype), np.asarray(y, dtype=y_dtype))

    xs_tr, ys_tr, xs_te, ys_te = [], [], [], []
    for u in users:
        x, y = prep(train_data, u)
        xs_tr.append(x)
        ys_tr.append(y)
        x, y = prep(test_data, u)
        xs_te.append(x)
        ys_te.append(y)

    train = stack_client_data(xs_tr, ys_tr, batch_size,
                              shuffle_seed=MNIST_SHUFFLE_SEED)
    test = stack_client_data(xs_te, ys_te, batch_size)
    xg_tr = np.concatenate([x for x in xs_tr if len(x)])
    yg_tr = np.concatenate([y for y in ys_tr if len(y)])
    xg_te = np.concatenate([x for x in xs_te if len(x)])
    yg_te = np.concatenate([y for y in ys_te if len(y)])
    return FederatedData(
        client_num=len(users), class_num=class_num, train=train, test=test,
        train_global=batch_global(xg_tr, yg_tr, batch_size),
        test_global=batch_global(xg_te, yg_te, batch_size))


def load_mnist(data_dir: str, batch_size: int = 10) -> FederatedData:
    """LEAF MNIST: 1000 clients, x = flat 784 floats, 10 classes
    (MNIST/data_loader.py:86-138; batch size 10 per benchmark/README.md)."""
    users, _, train_data, test_data = read_leaf_dirs(
        os.path.join(data_dir, "train"), os.path.join(data_dir, "test"))
    return _stack_leaf(users, train_data, test_data, batch_size, class_num=10)


def load_mnist_by_device_id(data_dir: str, device_id: str,
                            batch_size: int = 10) -> FederatedData:
    """Mobile variant: per-device train/test subtree
    (MNIST/data_loader.py:79-84)."""
    return load_mnist(os.path.join(data_dir, device_id), batch_size)


def load_shakespeare_leaf(data_dir: str, batch_size: int = 4) -> FederatedData:
    """LEAF shakespeare: x = 80-char crops, y = next char
    (shakespeare/data_loader.py + language_utils.py). We encode to the shared
    90-symbol vocab and emit full next-char targets (y shifted by one) so the
    same LM loss serves both shakespeare variants."""
    vocab = CharVocab()

    def encode(x_list, y_list):
        xs = np.asarray([[vocab.char_id(c) for c in s] for s in x_list],
                        dtype=np.int32)
        if xs.size == 0:
            xs = np.zeros((0, SHAKESPEARE_SEQ_LEN), np.int32)
        # LEAF y is the single next char; widen to a shifted sequence target
        ys_last = np.asarray([vocab.char_id(s[0] if s else " ")
                              for s in y_list], dtype=np.int32)
        ys = np.concatenate([xs[:, 1:], ys_last[:, None]], axis=1) \
            if len(xs) else np.zeros((0, SHAKESPEARE_SEQ_LEN), np.int32)
        return xs, ys

    users, _, train_data, test_data = read_leaf_dirs(
        os.path.join(data_dir, "train"), os.path.join(data_dir, "test"))
    return _stack_leaf(users, train_data, test_data, batch_size,
                       class_num=vocab.vocab_size, encode=encode)


def load_synthetic_leaf(data_dir: str, batch_size: int = 10,
                        class_num: int = 10) -> FederatedData:
    """LEAF synthetic_(a,b) json produced by generate_synthetic.py
    (data/synthetic_0.5_0.5/generate_synthetic.py:73-…)."""
    users, _, train_data, test_data = read_leaf_dirs(
        os.path.join(data_dir, "train"), os.path.join(data_dir, "test"))
    return _stack_leaf(users, train_data, test_data, batch_size,
                       class_num=class_num)
