"""``python -m fedml_tpu`` entry point (see fedml_tpu/experiments/main.py)."""

from fedml_tpu.experiments.main import main

if __name__ == "__main__":
    main()
