"""Secure aggregation: finite-field primitives, SecAgg masking, TurboAggregate.

The reference ships zero tests for its MPC kernel (mpc_function.py); these
validate every primitive against brute force / algebraic identities, then
check the TPU secagg path bit-exactly against plain aggregation.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from fedml_tpu.secure import (
    mod_inv, mod_div, prod_mod, lagrange_coeffs, bgw_encode, bgw_decode,
    lcc_encode, lcc_decode, lcc_encode_with_points, lcc_decode_with_points,
    additive_shares, pk_gen, key_agreement,
    quantize, dequantize, pairwise_masks, SecureCohortAggregator,
)
from fedml_tpu.secure.field import P_DEFAULT, pow_mod

P_SMALL = np.int64(97)


class TestFieldPrimitives:
    def test_mod_inv_brute_force(self):
        for a in range(1, 97):
            inv = mod_inv(a, P_SMALL)
            assert (a * int(inv)) % 97 == 1

    def test_mod_inv_vectorized_large_prime(self):
        a = np.array([2, 3, 12345, 2**30], dtype=np.int64)
        inv = mod_inv(a, P_DEFAULT)
        assert np.all(np.mod(a * inv, P_DEFAULT) == 1)

    def test_mod_div(self):
        assert int(mod_div(10, 5, P_SMALL)) == 2
        # 1/3 * 3 == 1
        assert int(np.mod(mod_div(1, 3, P_SMALL) * 3, P_SMALL)) == 1

    def test_prod_mod(self):
        vals = [5, 11, 20, 96]
        assert int(prod_mod(vals, P_SMALL)) == (5 * 11 * 20 * 96) % 97

    def test_pow_mod(self):
        assert int(pow_mod(np.int64(3), 45, P_SMALL)) == pow(3, 45, 97)

    def test_lagrange_partition_of_unity(self):
        # interpolating the constant-1 polynomial: rows must sum to 1
        alpha = np.arange(5, 9)
        beta = np.arange(1, 4)
        U = lagrange_coeffs(alpha, beta, P_SMALL)
        assert np.all(np.mod(U.sum(axis=1), P_SMALL) == 1)

    def test_lagrange_identity_at_nodes(self):
        # evaluating at the interpolation nodes gives the identity matrix
        beta = np.array([2, 5, 11])
        U = lagrange_coeffs(beta, beta, P_DEFAULT)
        assert np.array_equal(np.mod(U, P_DEFAULT), np.eye(3, dtype=np.int64))


class TestSecretSharing:
    def test_bgw_roundtrip(self):
        rng = np.random.RandomState(0)
        secret = rng.randint(0, 1000, size=(4, 6)).astype(np.int64)
        N, T = 7, 2
        shares = bgw_encode(secret, N, T, rng=np.random.RandomState(1))
        # any T+1 shares reconstruct
        idx = [1, 4, 6]
        rec = bgw_decode(shares[idx], idx)
        assert np.array_equal(rec, secret)

    def test_bgw_threshold_hides(self):
        # T shares alone give a different (wrong) reconstruction — the secret
        # is not determined by fewer than T+1 points
        secret = np.zeros((1, 4), dtype=np.int64)
        shares = bgw_encode(secret, 5, 2, rng=np.random.RandomState(2))
        rec = bgw_decode(shares[[0, 1]], [0, 1])
        assert not np.array_equal(rec, secret)

    def test_lcc_roundtrip_no_privacy(self):
        rng = np.random.RandomState(3)
        X = rng.randint(0, 1000, size=(6, 5)).astype(np.int64)
        N, K, T = 8, 3, 0
        enc = lcc_encode(X, N, K, T, rng=rng)
        survivors = [0, 2, 5]  # K+T = 3 suffice when T=0... degree K-1 poly
        dec = lcc_decode(enc[survivors], N, K, T, survivors)
        assert np.array_equal(dec, X.reshape(K, 2, 5).reshape(-1, 5))

    def test_lcc_roundtrip_with_privacy(self):
        rng = np.random.RandomState(4)
        X = rng.randint(0, 1000, size=(4, 3)).astype(np.int64)
        N, K, T = 7, 2, 2
        enc = lcc_encode(X, N, K, T, rng=rng)
        survivors = [0, 1, 3, 6]  # need K+T = 4
        dec = lcc_decode(enc[survivors], N, K, T, survivors)
        assert np.array_equal(dec.reshape(-1, 3), X)

    def test_lcc_with_points_roundtrip(self):
        rng = np.random.RandomState(5)
        X = rng.randint(0, 1000, size=(3, 4)).astype(np.int64)
        alpha = np.array([1, 2, 3])   # where X lives
        beta = np.array([11, 12, 13])  # where shares evaluate
        enc = lcc_encode_with_points(X, alpha, beta)
        back = lcc_decode_with_points(enc, beta, alpha)
        assert np.array_equal(back, X)

    def test_additive_shares_sum(self):
        x = np.arange(10, dtype=np.int64) * 7
        shares = additive_shares(x, 5, rng=np.random.RandomState(6))
        assert shares.shape == (5, 10)
        assert np.array_equal(np.mod(shares.sum(axis=0), P_DEFAULT), x)

    def test_key_agreement_symmetry(self):
        p, g = np.int64(2**31 - 1), 7
        sk_a, sk_b = 12345, 67890
        pk_a, pk_b = pk_gen(sk_a, p, g), pk_gen(sk_b, p, g)
        assert int(key_agreement(sk_a, pk_b, p, g)) == \
               int(key_agreement(sk_b, pk_a, p, g))


class TestSecAgg:
    def test_quantize_roundtrip(self):
        x = {"w": jnp.array([-1.5, 0.0, 0.25, 100.0])}
        out = dequantize(quantize(x))
        np.testing.assert_allclose(np.asarray(out["w"]),
                                   np.asarray(x["w"]), atol=1e-4)

    def test_masks_cancel(self):
        key = jax.random.key(0)
        tree = {"w": jnp.zeros((3, 4))}
        C = 5
        q = quantize(tree)
        total = jnp.zeros((3, 4), jnp.uint32)
        for c in range(C):
            m = pairwise_masks(key, jnp.asarray(c), C, q)
            total = total + m["w"]
        assert np.all(np.asarray(total) == 0)

    def test_masked_aggregate_matches_plain(self):
        rng = np.random.RandomState(0)
        C = 4
        updates = {"a": jnp.asarray(rng.randn(C, 3, 2), jnp.float32),
                   "b": jnp.asarray(rng.randn(C, 5), jnp.float32)}
        num = jnp.asarray([10.0, 20.0, 5.0, 15.0])
        agg = SecureCohortAggregator(C)
        secure = agg.aggregate_stacked(updates, num, jax.random.key(1))
        plain = jax.tree.map(
            lambda x: jnp.sum(
                x * num.reshape((-1,) + (1,) * (x.ndim - 1)), axis=0)
            / jnp.sum(num), updates)
        for k in ("a", "b"):
            np.testing.assert_allclose(np.asarray(secure[k]),
                                       np.asarray(plain[k]), atol=2e-4)

    def test_single_update_is_masked(self):
        # server must NOT learn an individual update: a lone masked update
        # decodes to noise, not the value
        agg = SecureCohortAggregator(3)
        upd = {"w": jnp.ones((4,))}
        masked = agg.mask_update(upd, 1.0, 0, jax.random.key(2))
        leaked = dequantize(masked)
        assert not np.allclose(np.asarray(leaked["w"]), 1.0, atol=0.1)


class TestTurboAggregate:
    def _build(self):
        from fedml_tpu.models import LogisticRegression
        from fedml_tpu.trainer.workload import ClassificationWorkload
        from fedml_tpu.data.stacking import stack_client_data, FederatedData
        from fedml_tpu.algorithms.turboaggregate import (
            TurboAggregate, TurboAggregateConfig)
        rng = np.random.RandomState(0)
        C = 8
        xs = [rng.randn(6, 10).astype(np.float32) for _ in range(C)]
        ys = [rng.randint(0, 3, 6).astype(np.int32) for _ in range(C)]
        data = FederatedData(client_num=C, class_num=3,
                             train=stack_client_data(xs, ys, batch_size=3))
        model = LogisticRegression(input_dim=10, output_dim=3)
        workload = ClassificationWorkload(model, num_classes=3)
        cfg = TurboAggregateConfig(comm_round=1, group_num=2,
                                   clients_per_group=4, drop_tolerance=1,
                                   lr=0.1, seed=0)
        ta = TurboAggregate(workload, data, cfg)
        params = workload.init(jax.random.key(0), jax.tree.map(
            lambda v: jnp.asarray(v[0, 0]),
            {k: data.train[k] for k in ("x", "y", "mask")}))
        return ta, params

    def test_round_runs_and_moves_params(self):
        ta, params = self._build()
        new = ta.train_round(params, 0)
        delta = max(jax.tree.leaves(jax.tree.map(
            lambda a, b: float(jnp.abs(a - b).max()), params, new)))
        assert delta > 0

    def test_dropout_recovery_matches_direct(self):
        ta, params = self._build()
        direct = ta.train_round(params, 0)
        recovered = ta.train_round(params, 0, dropped_groups=[1])
        err = max(jax.tree.leaves(jax.tree.map(
            lambda a, b: float(jnp.abs(a - b).max()), direct, recovered)))
        # quantization through the finite field costs at most ~1/scale
        assert err < 1e-3


class TestRingBudget:
    """ISSUE 11 satellite: quantize's fixed-point range is per-update,
    but the cohort sum of N clipped clients reaches N*clip — beyond
    ±2^31/scale the uint32 sum silently wraps and the aggregate decodes
    sign-flipped.  The budget is now validated at aggregator
    construction (fail loudly) or the scale auto-derives from the
    cohort size."""

    def test_explicit_scale_at_wrap_boundary_rejected(self):
        # 4 * 2^14 * 2^15 = 2^31 exactly: a full-clip cohort wraps
        with pytest.raises(ValueError, match="ring budget"):
            SecureCohortAggregator(4, scale=2.0**15, clip=2.0**14)

    def test_explicit_scale_below_boundary_accepted(self):
        agg = SecureCohortAggregator(4, scale=2.0**14, clip=2.0**14)
        assert agg.scale == 2.0**14

    def test_auto_scale_survives_full_clip_saturation(self):
        """The exact input that silently wrapped under the old default
        (scale 2^16): every weighted value saturates the clip, so the
        ring sum is N*clip*scale.  Auto-derived scale keeps it inside
        ±2^31 and the aggregate decodes correctly instead of
        sign-flipped."""
        C, clip = 4, 2.0**14
        agg = SecureCohortAggregator(C, clip=clip)  # scale auto-derived
        assert C * clip * agg.scale < 2.0**31
        # equal weights; every client's value is C*clip so the weighted
        # value (x * 1/C) sits exactly AT the clip — the historical wrap
        updates = {"w": jnp.full((C, 8), C * clip, jnp.float32)}
        num = jnp.ones(C)
        out = agg.aggregate_stacked(updates, num, jax.random.key(0))
        # true sum of clipped weighted values = C * clip (all positive);
        # a wrapped ring would decode this hugely NEGATIVE
        np.testing.assert_allclose(np.asarray(out["w"]), C * clip,
                                   rtol=1e-6)

    def test_ring_budget_helpers(self):
        from fedml_tpu.secure.secagg import (ring_budget_scale,
                                             validate_ring_budget)
        s = ring_budget_scale(8, 2.0**14)
        assert 8 * 2.0**14 * s < 2.0**31
        assert 8 * 2.0**14 * (s * 2) >= 2.0**31  # largest power of two
        validate_ring_budget(8, 2.0**14, s)  # no raise
        with pytest.raises(ValueError, match="ring budget"):
            validate_ring_budget(8, 2.0**14, s * 2)


class TestReviewRegressions:
    def test_no_ring_overflow_with_large_sample_counts(self):
        """Normalized-weight masking: huge sample counts must not wrap the
        uint32 ring (previously n_i-weighted values overflowed ±2^31/scale)."""
        C = 6
        rng = np.random.RandomState(7)
        updates = {"w": jnp.asarray(rng.randn(C, 8) * 100.0, jnp.float32)}
        num = jnp.asarray([1e4, 5e4, 2e4, 3e4, 1e4, 4e4], jnp.float32)
        agg = SecureCohortAggregator(C)
        secure = agg.aggregate_stacked(updates, num, jax.random.key(9))
        plain = jnp.sum(updates["w"] * num[:, None], axis=0) / jnp.sum(num)
        np.testing.assert_allclose(np.asarray(secure["w"]),
                                   np.asarray(plain), atol=5e-4)

    def test_lcc_decode_rejects_too_few_shares(self):
        rng = np.random.RandomState(8)
        X = rng.randint(0, 100, size=(4, 3)).astype(np.int64)
        enc = lcc_encode(X, 6, 2, 2, rng=rng)
        with pytest.raises(ValueError, match="K\\+T"):
            lcc_decode(enc[[0, 1]], 6, 2, 2, [0, 1])

    def test_lcc_shares_never_plaintext(self):
        """Disjoint alpha/beta grids: no worker's share may equal a secret
        chunk verbatim (the reference's overlapping grids leak chunks)."""
        rng = np.random.RandomState(9)
        X = rng.randint(0, P_DEFAULT, size=(4, 8)).astype(np.int64)
        N, K, T = 6, 2, 1
        enc = lcc_encode(X, N, K, T, rng=rng)
        chunks = X.reshape(K, 2, 8)
        for i in range(N):
            for k in range(K):
                assert not np.array_equal(enc[i], chunks[k])

    def test_turboaggregate_more_groups_than_clients(self):
        """Empty (all-padding) groups must neither NaN the model nor crash."""
        from fedml_tpu.models import LogisticRegression
        from fedml_tpu.trainer.workload import ClassificationWorkload
        from fedml_tpu.data.stacking import stack_client_data, FederatedData
        from fedml_tpu.algorithms.turboaggregate import (
            TurboAggregate, TurboAggregateConfig)
        rng = np.random.RandomState(1)
        C = 6  # < group_num * clients_per_group = 16
        xs = [rng.randn(4, 10).astype(np.float32) for _ in range(C)]
        ys = [rng.randint(0, 3, 4).astype(np.int32) for _ in range(C)]
        data = FederatedData(client_num=C, class_num=3,
                             train=stack_client_data(xs, ys, batch_size=2))
        workload = ClassificationWorkload(
            LogisticRegression(input_dim=10, output_dim=3), num_classes=3)
        cfg = TurboAggregateConfig(comm_round=1, group_num=4,
                                   clients_per_group=4, drop_tolerance=1)
        ta = TurboAggregate(workload, data, cfg)
        params = workload.init(jax.random.key(0), jax.tree.map(
            lambda v: jnp.asarray(v[0, 0]),
            {k: data.train[k] for k in ("x", "y", "mask")}))
        new = ta.train_round(params, 0)
        for leaf in jax.tree.leaves(new):
            assert np.all(np.isfinite(np.asarray(leaf)))

    def test_insufficient_group_size_asserts(self):
        from fedml_tpu.algorithms.turboaggregate import TurboAggregateConfig
        cfg = TurboAggregateConfig(clients_per_group=4, drop_tolerance=2)
        # N=4, T=2, K=2: N - T = 2 < K + T = 4 must be rejected at the
        # recovery path, not silently decoded from too few shares
        from fedml_tpu.models import LogisticRegression
        from fedml_tpu.trainer.workload import ClassificationWorkload
        from fedml_tpu.data.stacking import stack_client_data, FederatedData
        from fedml_tpu.algorithms.turboaggregate import TurboAggregate
        rng = np.random.RandomState(2)
        C = 8
        xs = [rng.randn(4, 10).astype(np.float32) for _ in range(C)]
        ys = [rng.randint(0, 3, 4).astype(np.int32) for _ in range(C)]
        data = FederatedData(client_num=C, class_num=3,
                             train=stack_client_data(xs, ys, batch_size=2))
        workload = ClassificationWorkload(
            LogisticRegression(input_dim=10, output_dim=3), num_classes=3)
        cfg.group_num = 2
        ta = TurboAggregate(workload, data, cfg)
        params = workload.init(jax.random.key(0), jax.tree.map(
            lambda v: jnp.asarray(v[0, 0]),
            {k: data.train[k] for k in ("x", "y", "mask")}))
        with pytest.raises(AssertionError, match="dropouts"):
            ta.train_round(params, 0, dropped_groups=[0])
