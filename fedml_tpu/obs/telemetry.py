"""Thread-safe counter/gauge/histogram registry with Prometheus text
exposition — the numeric half of the observability subsystem.

The reference's only telemetry is wandb scalar logging on rank 0
(FedAVGAggregator.py:136-162); nothing counts what the *communication
stack* actually did — sends, retries, dropped frames, dead silos.  After
PR 1 added retries/chaos/failure-detection, a stalled round became
indistinguishable from a retry storm.  This registry closes that gap the
same dependency-free way `MetricsSink` does metrics: stdlib only.

Design:

* **Null-object default** — ``get_registry()`` returns a `NullRegistry`
  until `enable()` is called.  Instrumented code caches metric handles at
  construction time, so a disabled run pays one ``is-enabled`` branch per
  hot-path event and allocates nothing per message.
* **naming contract** — every metric name must match
  ``fedml_[a-z0-9_]+`` and end in a unit suffix ``_total`` / ``_seconds``
  / ``_bytes`` / ``_ratio`` / ``_value`` (enforced at registration;
  linted by tests/test_metric_naming.py) so dashboards never chase
  renames.  ``_ratio`` exists for non-monotonic rate gauges and
  ``_value`` for non-monotonic unitless point-in-time gauges (update
  norms, delta norms) — Prometheus tooling treats ``*_total`` as
  counter-by-convention, so a gauge holding a measurement that goes up
  AND down must not wear it (count-valued state gauges like
  ``fedml_robust_quarantined_total`` keep ``_total`` by repo
  precedent).
* **exposition** — ``render_prometheus()`` emits the text format; an
  optional ``start_http_server(port)`` serves it at ``/metrics`` from a
  stdlib ThreadingHTTPServer daemon thread; ``snapshot()``/``save()``
  give the JSON form `obs/report.py` merges with metrics.jsonl.
"""

from __future__ import annotations

import bisect
import json
import logging
import os
import re
import threading
import time
from typing import Dict, Optional, Tuple

log = logging.getLogger(__name__)

NAME_RE = re.compile(
    r"^fedml_[a-z0-9_]+(_total|_seconds|_bytes|_ratio|_value)$")

# wall-clock-latency buckets (seconds); callers pass their own for
# count-valued histograms (quorum size, staleness)
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


class _NullMetric:
    """Shared no-op handle: every method is a pass, so disabled
    instrumentation costs one cached attribute call."""
    __slots__ = ()

    def inc(self, n=1):
        pass

    def dec(self, n=1):
        pass

    def set(self, v):
        pass

    def observe(self, v):
        pass

    @property
    def value(self):
        return 0.0


NULL_METRIC = _NullMetric()


class NullRegistry:
    """Disabled-mode registry: hands out the shared no-op metric."""
    enabled = False

    def counter(self, name: str, help: str = "", **labels):
        return NULL_METRIC

    def gauge(self, name: str, help: str = "", **labels):
        return NULL_METRIC

    def histogram(self, name: str, help: str = "", buckets=None, **labels):
        return NULL_METRIC

    def names(self):
        return []

    def snapshot(self) -> dict:
        return {}

    def render_prometheus(self) -> str:
        return ""

    def save(self, path: str) -> None:
        pass


class Counter:
    """Monotonic counter.  ``inc`` only (Prometheus contract)."""
    __slots__ = ("_lock", "value")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.value = 0.0

    def inc(self, n=1) -> None:
        if n < 0:
            raise ValueError(f"counters only go up; got inc({n})")
        with self._lock:
            self.value += n


class Gauge:
    """Point-in-time value: set / inc / dec."""
    __slots__ = ("_lock", "value")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.value = 0.0

    def set(self, v) -> None:
        with self._lock:
            self.value = float(v)

    def inc(self, n=1) -> None:
        with self._lock:
            self.value += n

    def dec(self, n=1) -> None:
        with self._lock:
            self.value -= n


class Histogram:
    """Fixed-bucket histogram (per-bucket counts + sum + count + min/max)."""
    __slots__ = ("_lock", "buckets", "counts", "sum", "count", "min", "max")

    def __init__(self, lock: threading.Lock, buckets=DEFAULT_BUCKETS):
        self._lock = lock
        self.buckets = tuple(float(b) for b in buckets)
        if list(self.buckets) != sorted(set(self.buckets)):
            raise ValueError(f"buckets must be strictly increasing, "
                             f"got {buckets}")
        self.counts = [0] * (len(self.buckets) + 1)  # last = +Inf
        self.sum = 0.0
        self.count = 0
        self.min = None
        self.max = None

    def observe(self, v) -> None:
        v = float(v)
        idx = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self.counts[idx] += 1
            self.sum += v
            self.count += 1
            if self.min is None or v < self.min:
                self.min = v
            if self.max is None or v > self.max:
                self.max = v

    def stats(self) -> dict:
        with self._lock:
            return {"count": self.count, "sum": self.sum,
                    "min": self.min, "max": self.max,
                    "mean": (self.sum / self.count) if self.count else None,
                    "buckets": {str(b): c for b, c in
                                zip(self.buckets, self.counts)} |
                               {"+Inf": self.counts[-1]}}


def _label_str(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class TelemetryRegistry:
    """Get-or-create metric families keyed by (name, labels).

    One lock serializes registration AND all metric mutation — federated
    hot paths are message-rate, not instruction-rate, so contention is
    negligible and the invariants are trivially safe under the actor
    threads (event loops, heartbeats, chaos timers, resilient senders).
    """
    enabled = True

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple[str, tuple], object] = {}
        self._kinds: Dict[str, str] = {}    # family name -> kind

    def _get(self, kind: str, name: str, labels: dict, factory):
        if not NAME_RE.match(name):
            raise ValueError(
                f"telemetry metric {name!r} violates the naming contract "
                f"fedml_[a-z0-9_]+ with a _total/_seconds/_bytes/_ratio "
                f"suffix")
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            have = self._kinds.get(name)
            if have is not None and have != kind:
                raise ValueError(f"metric {name!r} already registered as "
                                 f"{have}, not {kind}")
            self._kinds[name] = kind
            metric = self._metrics.get(key)
            if metric is None:
                metric = factory()
                self._metrics[key] = metric
            return metric

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        return self._get("counter", name, labels,
                         lambda: Counter(self._lock))

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        return self._get("gauge", name, labels, lambda: Gauge(self._lock))

    def histogram(self, name: str, help: str = "", buckets=None,
                  **labels) -> Histogram:
        return self._get(
            "histogram", name, labels,
            lambda: Histogram(self._lock, buckets or DEFAULT_BUCKETS))

    # -- export --------------------------------------------------------------
    def names(self):
        with self._lock:
            return sorted(self._kinds)

    def snapshot(self) -> dict:
        """JSON-able dump: {counters, gauges, histograms} keyed by the
        Prometheus series name (labels included)."""
        with self._lock:
            items = list(self._metrics.items())
            kinds = dict(self._kinds)
        out = {"ts": time.time(), "counters": {}, "gauges": {},
               "histograms": {}}
        for (name, labels), metric in sorted(items):
            series = name + _label_str(dict(labels))
            kind = kinds[name]
            if kind == "histogram":
                out["histograms"][series] = metric.stats()
            else:
                out[kind + "s"][series] = metric.value
        return out

    def render_prometheus(self) -> str:
        lines = []
        last_family = None
        # hold the registry lock for the WHOLE render: metric fields are
        # read directly (never via stats(), which would re-acquire), so a
        # concurrent observe() cannot produce a scrape whose buckets
        # disagree with its _sum/_count
        with self._lock:
            for (name, labels), metric in sorted(self._metrics.items()):
                kind = self._kinds[name]
                if name != last_family:
                    lines.append(f"# TYPE {name} {kind}")
                    last_family = name
                labels = dict(labels)
                if kind == "histogram":
                    cum = 0
                    for b, c in zip(metric.buckets + (float("inf"),),
                                    metric.counts):
                        cum += c
                        le = "+Inf" if b == float("inf") else repr(b)
                        lines.append(
                            f"{name}_bucket"
                            f"{_label_str(labels | {'le': le})} {cum}")
                    lines.append(f"{name}_sum{_label_str(labels)} "
                                 f"{metric.sum}")
                    lines.append(f"{name}_count{_label_str(labels)} "
                                 f"{metric.count}")
                else:
                    lines.append(f"{name}{_label_str(labels)} "
                                 f"{metric.value}")
        return "\n".join(lines) + ("\n" if lines else "")

    def save(self, path: str) -> None:
        """Atomic JSON snapshot (tmp + os.replace — a crashed run still
        leaves the previous readable snapshot, never a torn file)."""
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.snapshot(), f, indent=2, sort_keys=True)
        os.replace(tmp, path)


def link_counter(registry, cache: dict, name: str, src, dst):
    """Get-or-create a per-link counter through a caller-held cache: one
    dict lookup per message instead of registry-lock + label-string
    formatting.  The shared hot-path idiom for every transport flavor
    (send/recv/bytes in `Transport`, wire bytes in `LocalHub`)."""
    key = (name, src, dst)
    counter = cache.get(key)
    if counter is None:
        counter = registry.counter(name, link=f"{src}->{dst}")
        cache[key] = counter
    return counter


# -- process-global registry -------------------------------------------------

_registry = NullRegistry()


def get_registry():
    """The process registry: a `NullRegistry` until `enable()` runs.
    Instrumented constructors cache handles from this — enable telemetry
    BEFORE building transports/actors."""
    return _registry


def enable(registry: Optional[TelemetryRegistry] = None) -> TelemetryRegistry:
    global _registry
    if not isinstance(_registry, TelemetryRegistry):
        _registry = registry if registry is not None else TelemetryRegistry()
    return _registry


def disable() -> None:
    global _registry
    _registry = NullRegistry()


def start_http_server(port: int, registry=None, host: str = ""):
    """Serve ``GET /metrics`` (Prometheus text) and ``GET /healthz`` on
    ``port`` from a daemon thread.  Returns the server — or **None when
    the bind fails** (port already taken by a sibling run): a training
    job must never crash over its scrape endpoint, so the failure warns
    and the run continues unexported.  Call ``.shutdown()`` to stop it."""
    import http.server

    reg = registry if registry is not None else get_registry()
    if isinstance(reg, NullRegistry):
        # fail loud, not silent: a scrape endpoint over the Null registry
        # would serve an empty exposition forever and every dashboard
        # would read "healthy, no traffic" — the exact lie --metrics_port
        # exists to prevent.  Callers must enable() first.
        raise ValueError(
            "start_http_server needs a live telemetry registry, but "
            "telemetry is disabled (NullRegistry): call "
            "telemetry.enable() first (--telemetry / --metrics_port "
            "imply it in the experiment runner)")

    class _Handler(http.server.BaseHTTPRequestHandler):
        # socket read timeout (StreamRequestHandler applies it to the
        # connection): a scraper that connects and then never sends its
        # request line times out and closes instead of pinning a
        # handler thread forever
        timeout = 5

        def do_GET(self):
            # drop query strings: probes append cache-busters
            path = self.path.split("?", 1)[0].rstrip("/")
            if path == "/healthz":
                body = b'{"status": "ok"}'
                ctype = "application/json"
            elif path in ("", "/metrics"):
                body = reg.render_prometheus().encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            else:
                self.send_error(404)
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # quiet: no per-scrape stderr spam
            pass

    try:
        server = http.server.ThreadingHTTPServer((host, port), _Handler)
    except OSError as e:
        log.warning("telemetry: cannot serve /metrics on port %d (%s) — "
                    "continuing without the HTTP endpoint", port, e)
        return None
    server.daemon_threads = True
    thread = threading.Thread(target=server.serve_forever, daemon=True,
                              name=f"telemetry-http-{port}")
    thread.start()
    return server
