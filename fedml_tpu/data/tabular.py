"""Vertically-partitioned tabular datasets (lending_club loan, NUS-WIDE).

Contract (matching the reference's VFL loaders): party-split feature
matrices + binary labels,
``([Xa_train, Xb_train(, Xc_train), y_train], [Xa_test, ..., y_test])``
(``lending_club_loan/lending_club_dataset.py:141-188``,
``NUS_WIDE/nus_wide_dataset.py:73-163``).

* lending_club: one csv of loan records; party A gets borrower-qualification
  features, party B loan/debt/repayment features (feature groups from
  lending_club_feature_group.py); target Good/Bad loan; standard-scaled.
* NUS-WIDE: party A = 634-dim low-level image features, party B = 1000-dim
  tag features; label = one selected concept vs. the rest (neg_label -1 or 0).

Both gate on file availability; ``synthetic_vfl_parties`` provides the
hermetic twin with the same return shape.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

VflSplit = Tuple[List[np.ndarray], List[np.ndarray]]


def _standard_scale(x: np.ndarray) -> np.ndarray:
    mu = x.mean(0, keepdims=True)
    sd = x.std(0, keepdims=True)
    return (x - mu) / np.where(sd == 0, 1.0, sd)


def load_lending_club_two_party(data_dir: str, csv_name: str = "loan.csv",
                                max_rows: Optional[int] = None) -> VflSplit:
    """Party A = qualification features, party B = loan behavior features,
    y = bad-loan indicator, 80/20 split (lending_club_dataset.py:141-162).
    Categorical columns are label-encoded; non-numeric leftovers dropped."""
    import pandas as pd
    df = pd.read_csv(os.path.join(data_dir, csv_name), nrows=max_rows,
                     low_memory=False)
    bad = {"Charged Off", "Default",
           "Does not meet the credit policy. Status:Charged Off",
           "In Grace Period", "Late (16-30 days)", "Late (31-120 days)"}
    y = df["loan_status"].isin(bad).astype(np.float32).values[:, None]
    df = df.drop(columns=["loan_status"])
    for col in df.columns:
        if df[col].dtype == object:
            df[col] = df[col].astype("category").cat.codes
    df = df.fillna(0)
    # qualification-flavored columns to party A, the rest to party B
    a_cols = [c for c in df.columns if any(k in c for k in (
        "emp", "home", "annual_inc", "verification", "zip", "addr",
        "grade", "purpose"))]
    b_cols = [c for c in df.columns if c not in a_cols]
    Xa = _standard_scale(df[a_cols].values.astype(np.float32))
    Xb = _standard_scale(df[b_cols].values.astype(np.float32))
    n_tr = int(0.8 * len(y))
    return ([Xa[:n_tr], Xb[:n_tr], y[:n_tr]],
            [Xa[n_tr:], Xb[n_tr:], y[n_tr:]])


def load_nus_wide_two_party(data_dir: str, selected_labels: Sequence[str],
                            neg_label: int = -1,
                            n_samples: int = -1) -> VflSplit:
    """NUS-WIDE: Xa = concatenated low-level features (Low_Level_Features/
    *_Train.dat), Xb = 1000-d tags (NUS_WID_Tags/Tags1k), y from
    Groundtruth/TrainTestLabels — positive = first selected label
    (nus_wide_dataset.py:23-120)."""
    import pandas as pd
    lf_dir = os.path.join(data_dir, "Low_Level_Features")
    feats = []
    for fn in sorted(os.listdir(lf_dir)):
        if fn.endswith("_Train.dat"):
            feats.append(pd.read_csv(os.path.join(lf_dir, fn), sep=" ",
                                     header=None).dropna(axis=1).values)
    Xa = np.concatenate(feats, axis=1).astype(np.float32)
    Xb = pd.read_csv(
        os.path.join(data_dir, "NUS_WID_Tags", "Train_Tags1k.dat"),
        sep="\t", header=None).dropna(axis=1).values.astype(np.float32)

    lab_dir = os.path.join(data_dir, "Groundtruth", "TrainTestLabels")
    cols = []
    for lbl in selected_labels:
        v = pd.read_csv(os.path.join(lab_dir, f"Labels_{lbl}_Train.txt"),
                        header=None).values.reshape(-1)
        cols.append(v)
    L = np.stack(cols, axis=1)
    sel = L.sum(1) == 1  # examples with exactly one of the selected concepts
    y = np.where(L[sel, 0] == 1, 1, neg_label).astype(np.float32)[:, None]
    Xa, Xb = Xa[sel], Xb[sel]
    if n_samples > 0:
        Xa, Xb, y = Xa[:n_samples], Xb[:n_samples], y[:n_samples]
    n_tr = int(0.8 * len(y))
    return ([Xa[:n_tr], Xb[:n_tr], y[:n_tr]],
            [Xa[n_tr:], Xb[n_tr:], y[n_tr:]])


def synthetic_vfl_parties(n_samples: int = 256,
                          feature_dims: Sequence[int] = (16, 24),
                          seed: int = 0, neg_label: int = 0) -> VflSplit:
    """Hermetic VFL twin: k parties' features jointly linearly separate y."""
    rng = np.random.RandomState(seed)
    Xs = [rng.randn(n_samples, d).astype(np.float32) for d in feature_dims]
    ws = [rng.randn(d) for d in feature_dims]
    logits = sum(x @ w for x, w in zip(Xs, ws))
    y = np.where(logits > 0, 1, neg_label).astype(np.float32)[:, None]
    n_tr = int(0.8 * n_samples)
    return ([x[:n_tr] for x in Xs] + [y[:n_tr]],
            [x[n_tr:] for x in Xs] + [y[n_tr:]])
