"""Performance flight recorder: the per-round cost breakdown the perf
trajectory has been missing (ROADMAP item 5b).

Three instruments, stdlib-only like the rest of `obs/`:

* **`PerfRecorder`** — one structured ``perf.jsonl`` line per completed
  round/version: phase wall-times (broadcast serialize, straggler wait,
  admission, defended aggregate, checkpoint, publish), wire bytes
  in/out (deltas of the PR 2 comm counters), the round's **peak host
  RSS watermark**, and the recompile count.  Each line is formatted
  fully before ONE ``write()`` call on an O_APPEND descriptor, so a
  crash can tear at most the final line — which every reader here
  (`trend.load_ledger`, `report.load_jsonl`) already tolerates.
* **`RssSampler`** — a daemon thread sampling ``VmRSS`` from
  ``/proc/self/status`` (no new deps); ``reset_peak()`` gives per-round
  watermarks.  This is the exact instrument ROADMAP item 2's "server
  RSS flat in cohort size" success criterion needs.
* **`RecompileSentry`** — tracks the jit cache sizes of registered hot
  functions (`make_defended_aggregate` products, the instrumented
  train fn).  Cache growth after the first check is a RECOMPILE:
  counted in ``fedml_perf_recompiles_total``, warned in production,
  and raised as `RecompileError` under ``strict`` (test mode) — the
  PR 5 double-compile class of bug (round-0 numpy globals vs later jax
  outputs keying two cache entries) can never land silently again.

`SloEvaluator` sits on top of the telemetry registry: rolling SLO
values (round-duration p95, serve shed rate, torn-frame rate,
quarantine events per round, device-memory headroom) exported as
``fedml_slo_*`` gauges with a per-SLO breach counter; it backs the
serve frontend's ``/healthz?deep=1`` mode (200 while every SLO holds,
503 on breach).

A `fedml_tpu.obs.device.DeviceRecorder` attaches via ``device=``: each
ledger line then carries a ``device`` section (per-device memory
watermarks, the round's named compile ledger, achieved-FLOP/s and an
honest MFU) and the sentry's recompile verdicts name the arg
shape/dtype that changed.  Ledgers without the section keep validating
— the device observatory is additive.
"""

from __future__ import annotations

import json
import logging
import os
import re
import threading
import time
from typing import Callable, Dict, Optional

from fedml_tpu.obs import critical_path as _cpath
from fedml_tpu.obs import telemetry
from fedml_tpu.obs.health import HEALTH_SLOS
from fedml_tpu.utils.journal import durable_append

log = logging.getLogger(__name__)

# the canonical phase vocabulary (a ledger line may carry a subset —
# e.g. no checkpoint phase on rounds the save_every gate skips; the
# aggregate span is named by what ran: "defended_aggregate" only when a
# make_defended_aggregate product is wired, plain "aggregate" otherwise,
# so a defended run never compares against an undefended baseline under
# one label)
PHASES = ("broadcast_serialize", "straggler_wait", "staging", "fold",
          "admission", "health", "aggregate", "defended_aggregate",
          "checkpoint", "publish",
          # secure aggregation (secure/protocol.py): advert/roster relay
          # time and the barrier-close share-reveal + reconstruction.
          # Phase names are open vocabulary to every reader
          # (trend.phase_medians keys on whatever a ledger carries), so
          # pre-secagg ledgers keep validating and gating unchanged.
          "mask_agreement", "unmask",
          # crash consistency (utils/journal.py): the durable round
          # journal's record appends + periodic fold-state snapshots on
          # the receive path — host-side I/O, never a trace
          "journal",
          # cross-device mega-cohort engine (algorithms/cross_device.py):
          # one compiled wave's gather + train + summary, accumulated
          # across the round's waves (fold/admission/health keep their
          # own phases, shared with the actor paths)
          "wave",
          # sharded global-model spine (fedml_tpu/shard_spine): the
          # per-shard defended finalize (one XLA program or fused
          # Pallas launch per shard) gets its OWN label so the trend
          # gate never compares a sharded round against a replicated
          # baseline under one name; fold/admission/journal phases are
          # shared with the replicated path
          "shard_finalize",
          # ingest observatory (obs/critical_path.py): per-upload codec
          # decode on the server receive path — its own label so the
          # attribution sweep can separate wire-format cost from fold
          "decode")


# ---------------------------------------------------------------------------
# RSS watermark sampler
# ---------------------------------------------------------------------------

def read_rss_bytes() -> Optional[int]:
    """Current resident set size from ``/proc/self/status`` (VmRSS).
    Returns None where /proc is unavailable (non-Linux) — the recorder
    then ledgers ``rss: null`` instead of guessing."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024  # kB -> bytes
    except OSError:
        return None
    return None


class RssSampler:
    """Daemon thread tracking the peak of ``read_rss_bytes()``.

    ``reset_peak()`` returns the watermark since the previous reset and
    restarts it from the CURRENT value — the per-round watermark
    protocol.  ``start``/``stop`` are idempotent and ``stop`` joins the
    thread, so owners can assert no thread leaks."""

    def __init__(self, interval_s: float = 0.05):
        self.interval_s = interval_s
        self._lock = threading.Lock()
        self._peak: Optional[int] = None
        self._current: Optional[int] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def sample(self) -> Optional[int]:
        rss = read_rss_bytes()
        if rss is not None:
            with self._lock:
                self._current = rss
                if self._peak is None or rss > self._peak:
                    self._peak = rss
        return rss

    @property
    def peak_bytes(self) -> Optional[int]:
        with self._lock:
            return self._peak

    def reset_peak(self) -> Optional[int]:
        """Return the watermark since the last reset; restart it from a
        fresh sample (never carry a stale peak into the next round)."""
        rss = read_rss_bytes()
        with self._lock:
            out = self._peak
            self._peak = self._current = rss
        return out

    def start(self) -> "RssSampler":
        if self._thread is not None or read_rss_bytes() is None:
            return self
        self._stop.clear()
        self.sample()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="perf-rss-sampler")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.sample()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


# ---------------------------------------------------------------------------
# recompile sentry
# ---------------------------------------------------------------------------

class RecompileError(RuntimeError):
    """Strict-mode verdict: a registered hot function recompiled after
    its baseline round — a silent perf regression, not a crash."""


class RecompileSentry:
    """Track jit cache sizes of registered hot functions.

    The FIRST ``check()`` per function records its baseline (round-0
    compiles are expected); later checks count any GROWTH as recompiles:
    ``fedml_perf_recompiles_total`` ticks, production warns, ``strict``
    raises `RecompileError`.  A shrunk cache (explicit clear) re-baselines
    silently.

    When the device observatory wraps a registered function
    (`obs.device.DeviceRecorder.instrument`), every call's arg
    shape/dtype signature lands here via ``note_signature`` — a firing
    verdict then NAMES the arg that changed instead of reporting a bare
    count, turning "something retraced" into an actionable diff."""

    def __init__(self, strict: bool = False, registry=None):
        self.strict = strict
        self._fns: Dict[str, Callable] = {}
        self._baseline: Dict[str, int] = {}
        # last two DISTINCT call signatures per fn (note_signature): the
        # observable projection of the jit cache key the verdict diffs
        self._sig_cur: Dict[str, tuple] = {}
        self._sig_prev: Dict[str, tuple] = {}
        reg = registry if registry is not None else telemetry.get_registry()
        self._c_recompiles = reg.counter("fedml_perf_recompiles_total")

    def register(self, name: str, fn) -> bool:
        """Register a hot function; returns False (and stays silent at
        check time) when it exposes no ``_cache_size`` probe."""
        if getattr(fn, "_cache_size", None) is None:
            log.debug("recompile sentry: %r has no _cache_size; skipped",
                      name)
            return False
        self._fns[name] = fn
        return True

    def note_signature(self, name: str, sig) -> None:
        """Record a registered fn's latest call signature (fed by the
        device observatory's wrappers).  Only the last two distinct
        signatures are kept — exactly what a recompile diff needs."""
        sig = tuple(sig)
        cur = self._sig_cur.get(name)
        if cur is not None and cur != sig:
            self._sig_prev[name] = cur
        self._sig_cur[name] = sig

    def signature_change(self, name: str) -> str:
        """The prev -> cur call-signature diff for ``name`` ("" when no
        change was observed or signatures were never fed)."""
        prev, cur = self._sig_prev.get(name), self._sig_cur.get(name)
        if prev is None or cur is None or prev == cur:
            return ""
        from fedml_tpu.obs.device import signature_diff
        return signature_diff(prev, cur)

    def names(self):
        return sorted(self._fns)

    def cache_sizes(self) -> Dict[str, int]:
        out = {}
        for name, fn in self._fns.items():
            try:
                out[name] = int(fn._cache_size())
            except Exception:  # noqa: BLE001 — fn mid-teardown
                continue
        return out

    def check(self, round_idx) -> Dict[str, int]:
        """Returns ``{fn_name: new_entries}`` for functions that
        recompiled since the last check (empty on a clean round)."""
        events: Dict[str, int] = {}
        for name, size in self.cache_sizes().items():
            prev = self._baseline.get(name)
            self._baseline[name] = size
            if prev is None or prev == 0 or size <= prev:
                # baseline round; an empty-cache baseline (the fn was
                # registered but not yet CALLED — e.g. round 0 closed
                # with no admissible uploads, so its first compile lands
                # later and is not a REcompile); or an explicit clear
                continue
            events[name] = size - prev
        total = sum(events.values())
        if total:
            self._c_recompiles.inc(total)
            parts = []
            for k, v in sorted(events.items()):
                part = f"{k}:+{v}"
                diff = self.signature_change(k)
                if diff:
                    part += f" [{diff}]"
                # consume the diff: it explains THIS verdict only — a
                # later same-signature recompile (the numpy-vs-jax
                # double-compile class) must not be decorated with a
                # stale, unrelated shape change
                self._sig_prev.pop(k, None)
                parts.append(part)
            detail = ", ".join(parts)
            msg = (f"recompile sentry: round {round_idx}: {total} new jit "
                   f"cache entr{'y' if total == 1 else 'ies'} after the "
                   f"baseline round ({detail}) — a hot function is "
                   f"retracing every round")
            if self.strict:
                raise RecompileError(msg)
            log.warning(msg)
        return events


# ---------------------------------------------------------------------------
# the per-round ledger
# ---------------------------------------------------------------------------

class _PhaseTimer:
    __slots__ = ("_rec", "_name", "_t0")

    def __init__(self, rec: "PerfRecorder", name: str):
        self._rec = rec
        self._name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._rec.add_phase(self._name, time.perf_counter() - self._t0)
        return False


# wire accounting: both byte-counter families carry ``link="src->dst"``
# labels (gRPC/MQTT count send_bytes, the codec-roundtrip hub counts
# wire_bytes), so the ledger splits them by DIRECTION relative to the
# recording node: out = links leaving it, in = links entering it.  The
# split is honest per process — a registry only holds what its own
# transports counted, so on multi-process wires (gRPC) inbound bytes
# read 0 until a receive path counts them; the in-process hub sees both
# directions of every link.
_BYTE_FAMILIES = ("fedml_comm_send_bytes_total",
                  "fedml_comm_wire_bytes_total")
_LINK_RE = re.compile(r'link="([^"]*)->([^"]*)"')


class PerfRecorder:
    """Own the round lifecycle: ``round_start`` → ``phase(...)`` spans /
    ``add_phase`` accumulations → ``round_end`` writes one ledger line.

    Thread-safety: phase accumulation may run on receive threads
    (admission screens in `_on_model`) while the round closes on the
    event loop — the accumulator dict is lock-guarded.  The ledger file
    is opened per line in append mode and written with ONE ``write()``
    call, so concurrent writers (a sync server and an async server
    sharing a run dir would be a misconfiguration anyway) can interleave
    lines but never interleave bytes of a line on POSIX O_APPEND."""

    def __init__(self, path: str, node: str = "server",
                 rss_interval_s: float = 0.05, strict_recompiles: bool = False,
                 registry=None, node_index: int = 0, device=None):
        self.path = path
        # optional device & compile observatory (obs/device.DeviceRecorder):
        # when attached, every ledger line gains a ``device`` section —
        # per-device memory watermarks, the round's named compile ledger,
        # and the honest MFU gauge (readers without it keep validating)
        self.device = device
        self.node = node
        self.node_index = node_index  # wire-byte direction split anchor
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        # one ledger == one run: a leftover file from a previous run at
        # the same path would splice two runs together — the second
        # run's compile-paying round 0 lands mid-file, poisoning the
        # trend gate's skip-first-round medians and the recompile gate's
        # baseline-row forgiveness.  Rotate it aside instead of
        # appending (or silently destroying a crashed run's evidence).
        if os.path.exists(path):
            os.replace(path, path + ".prev")
        reg = registry if registry is not None else telemetry.get_registry()
        self._registry = reg
        self.sentry = RecompileSentry(strict=strict_recompiles, registry=reg)
        self.rss = RssSampler(interval_s=rss_interval_s)
        self._lock = threading.Lock()
        self._phases: Dict[str, float] = {}
        self._round: Optional[int] = None
        self._round_t0: Optional[float] = None
        self._wire0 = (0.0, 0.0)
        self._g_rss = reg.gauge("fedml_perf_rss_peak_bytes")
        self._c_rounds = reg.counter("fedml_perf_rounds_total")
        self._h_phase: Dict[str, object] = {}
        self._closed = False
        self._ledger_disabled = False
        # round critical-path observatory (obs/critical_path.py): armed
        # per round in round_start, reduced into the line's
        # ``critical_path`` record at round_end — every ledger line
        # carries one, on every algorithm that rides this recorder
        self.cpath: Optional[_cpath.RoundCriticalPath] = None
        self._ingest = _cpath.IngestGauges(reg)

    # -- registration --------------------------------------------------------
    def register_jit(self, name: str, fn) -> bool:
        """Register a hot function with the recompile sentry."""
        return self.sentry.register(name, fn)

    def instrument_jit(self, name: str, fn):
        """Register ``fn`` with the recompile sentry AND — when the
        device observatory is attached — wrap it with compile-ledger +
        FLOPs instrumentation.  Returns the callable the caller should
        use in ``fn``'s place (``fn`` itself when no device recorder is
        on; the wrapper forwards the ``_cache_size`` probe either way)."""
        self.sentry.register(name, fn)
        if self.device is not None:
            fn = self.device.instrument(name, fn, sentry=self.sentry)
        return fn

    # -- wire accounting -----------------------------------------------------
    def _wire_totals(self):
        counters = self._registry.snapshot().get("counters", {})
        me = str(self.node_index)
        out = inn = 0.0
        for series, v in counters.items():
            if not series.startswith(_BYTE_FAMILIES):
                continue
            m = _LINK_RE.search(series)
            if m is None:
                continue  # unlabeled byte series: direction unknowable
            if m.group(1) == me:
                out += v
            elif m.group(2) == me:
                inn += v
        return out, inn

    # -- round lifecycle -----------------------------------------------------
    def round_start(self, round_idx) -> None:
        if self._round is None:
            self.rss.start()
        with self._lock:
            self._phases = {}
        self._round = round_idx
        self._round_t0 = time.perf_counter()
        self.cpath = _cpath.RoundCriticalPath(t0=self._round_t0)
        self.rss.reset_peak()
        self._wire0 = self._wire_totals()
        if self.device is not None:
            self.device.round_start()

    def phase(self, name: str) -> _PhaseTimer:
        """Context manager accumulating wall time into the current
        round's ``name`` phase (re-entering the same phase ADDS — the
        admission screen runs once per upload)."""
        return _PhaseTimer(self, name)

    def add_phase(self, name: str, seconds: float) -> None:
        with self._lock:
            self._phases[name] = self._phases.get(name, 0.0) + float(seconds)
        # every caller follows the measure-then-add idiom (the sample
        # ENDED now), so the critical-path accumulator gets an honest
        # ``[now - seconds, now)`` interval for the overlap sweep
        cp = self.cpath
        if cp is not None:
            cp.note(name, float(seconds))

    def note_arrival(self) -> None:
        """One upload landed off the wire (receive-path handlers call
        this): stamps the critical-path arrival timeline that classifies
        the round's idle time into network/straggler/barrier_wait."""
        cp = self.cpath
        if cp is not None:
            cp.note_arrival()

    def round_end(self, round_idx, **extra) -> Optional[dict]:
        """Close the round: sentry check, RSS watermark, wire deltas,
        one ledger line.  Returns the line dict (None when no round was
        open).  ``extra`` lands verbatim in the line (quorum size,
        version tags, ...)."""
        if self._round is None:
            return None
        # the sentry runs FIRST so a strict-mode RecompileError fires
        # before a misleading clean line could be written
        recompile_events = self.sentry.check(round_idx)
        rss_peak = self.rss.reset_peak()
        self.rss.sample()
        rss_now = self.rss.peak_bytes
        wire1 = self._wire_totals()
        with self._lock:
            phases = dict(self._phases)
            self._phases = {}
        round_s = (time.perf_counter() - self._round_t0
                   if self._round_t0 is not None else None)
        self._round = None
        line = {
            "round": round_idx,
            "ts": time.time(),
            "node": self.node,
            "round_s": round_s,
            "phases": {k: round(v, 6) for k, v in sorted(phases.items())},
            "wire": {"bytes_out": int(wire1[0] - self._wire0[0]),
                     "bytes_in": int(wire1[1] - self._wire0[1])},
            "rss": (None if rss_peak is None else
                    {"peak_bytes": int(rss_peak),
                     "current_bytes": None if rss_now is None
                     else int(rss_now)}),
            "recompiles": sum(recompile_events.values()),
            "jit_cache_sizes": self.sentry.cache_sizes(),
        }
        if recompile_events:
            line["recompiled"] = recompile_events
        if self.device is not None:
            line["device"] = self.device.round_snapshot(round_s)
        line.update(extra)
        cp, self.cpath = self.cpath, None
        if cp is not None:
            # known compile wall time (device observatory's per-round
            # compile ledger) is carved into the ``compile`` bucket
            compile_s = sum(
                float(e.get("wall_s") or 0.0)
                for e in (line.get("device") or {}).get("compiles") or ()
                if isinstance(e, dict))
            record = cp.finalize(duration=round_s, compile_s=compile_s)
            line["critical_path"] = record
            self._ingest.export(record, line["wire"]["bytes_in"])
        self._write(line)
        self._c_rounds.inc()
        if rss_peak is not None:
            self._g_rss.set(rss_peak)
        for name, dt in phases.items():
            h = self._h_phase.get(name)
            if h is None:
                h = self._registry.histogram("fedml_perf_phase_seconds",
                                             phase=name)
                self._h_phase[name] = h
            h.observe(dt)
        return line

    def _write(self, line: dict) -> None:
        if self._ledger_disabled:
            return
        data = json.dumps(line, sort_keys=True) + "\n"
        # one write() on an O_APPEND fd: a crash tears at most the tail.
        # A disk fault (ENOSPC/EIO — real or injected through the
        # utils.journal seam) must never kill the round loop: warn ONCE
        # and disable the ledger; the lines already on disk stay a valid
        # (truncated) trend-gate input.
        try:
            durable_append(self.path, data, channel="perf_ledger")
        except OSError as e:
            self._ledger_disabled = True
            log.warning("perf ledger append failed (%s); disabling the "
                        "ledger — training continues unledgered", e)

    def close(self) -> None:
        """Stop the sampler thread; safe to call twice.  An open round
        is NOT flushed — a half-measured round would ledger as a
        misleadingly fast one."""
        if self._closed:
            return
        self._closed = True
        self.rss.stop()


# ---------------------------------------------------------------------------
# SLO evaluator
# ---------------------------------------------------------------------------

def histogram_quantile(stats: dict, q: float) -> Optional[float]:
    """Upper-bound quantile estimate from a snapshot histogram dict
    (``{"count": n, "buckets": {bound: count, "+Inf": n_inf}}``): the
    smallest bucket bound whose cumulative count covers ``q`` of the
    observations.  +Inf-bucket answers fall back to the observed max
    (the histogram knows nothing finer).  None on an empty histogram."""
    count = stats.get("count") or 0
    if not count:
        return None
    buckets = stats.get("buckets") or {}
    finite = sorted(((float(b), c) for b, c in buckets.items()
                     if b != "+Inf"), key=lambda x: x[0])
    need = q * count
    cum = 0
    for bound, c in finite:
        cum += c
        if cum >= need:
            return bound
    return stats.get("max")


# default objectives; override per-deployment via the ``--slo`` spec
# ("name=value,...") or the constructor's thresholds dict.  The
# health_* objectives gate on the learning-health gauges the
# `obs/health.HealthAccumulator` exports each round — absent gauges
# (health off) evaluate vacuously healthy, like every other
# traffic-free objective.
DEFAULT_SLOS = {
    "round_duration_p95_seconds": 60.0,   # p95 round wall time
    "serve_shed_rate": 0.05,              # shed / submitted requests
    "torn_frame_rate": 0.01,              # torn frames / received msgs
    "quarantine_rate": 0.5,               # quarantine events / round
    # device-memory headroom (obs/device.py): worst per-device
    # bytes_in_use / bytes_limit the observatory exported last round —
    # breach means the next cohort/model growth OOMs the chip, the exact
    # signal ROADMAP items 1/3 gate on.  Backends without allocator
    # limits (CPU live-arrays fallback) never export the gauge, so the
    # objective evaluates vacuously there.
    "device_mem_utilization_ratio": 0.92,
    # worst-WORKER serve queue fill (ISSUE 15's multi-worker pool):
    # every MicroBatcher/DecodeScheduler exports qsize/depth as a
    # worker-labeled gauge; the objective reads the MAX across them so
    # one wedged worker breaches even while the pool average looks
    # healthy.  This is also what tiered admission sheds on (via
    # TierGate), so load shedding and deep-healthz always agree.
    "serve_queue_utilization_ratio": 0.9,
    **HEALTH_SLOS,                        # drift alarms (obs/health.py)
}


def parse_slo_spec(spec: str) -> Dict[str, float]:
    """Parse ``"round_duration_p95_seconds=10,serve_shed_rate=0.01"``;
    unknown SLO names fail loudly (a typo'd objective silently never
    evaluating is the exact blindness this module exists to end)."""
    out: Dict[str, float] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"--slo entries are name=value, got {part!r}")
        name, _, value = part.partition("=")
        name = name.strip()
        if name not in DEFAULT_SLOS:
            raise ValueError(f"unknown SLO {name!r}; available: "
                             f"{sorted(DEFAULT_SLOS)}")
        out[name] = float(value)
    return out


class SloEvaluator:
    """Rolling SLO evaluation over a telemetry registry snapshot.

    ``evaluate()`` computes each objective, exports it as a
    ``fedml_slo_*`` gauge, bumps the per-SLO breach counter when the
    objective is violated, and returns the full verdict dict.  Breach
    counting belongs to the ROUND cadence (the runners' per-round/
    per-version call): query paths — ``healthy()``, the serve frontend's
    ``/healthz?deep=1`` — pass ``count_breaches=False`` so one sustained
    breach counts per round, not per LB probe (a 1 s prober would
    otherwise inflate ``fedml_slo_breaches_total`` ~60x and break any
    "breaches > N" alert threshold)."""

    def __init__(self, registry=None, thresholds: Optional[dict] = None):
        reg = (registry if registry is not None
               else telemetry.get_registry())
        self._registry = reg
        unknown = set(thresholds or {}) - set(DEFAULT_SLOS)
        if unknown:
            raise ValueError(f"unknown SLOs {sorted(unknown)}; available: "
                             f"{sorted(DEFAULT_SLOS)}")
        self.thresholds = {**DEFAULT_SLOS, **(thresholds or {})}
        # literal names: the source-scan metric lint
        # (tests/test_metric_naming.py) pins these series.  The rate
        # gauges wear _ratio, not _total — they go down as well as up
        self._gauges = {
            "round_duration_p95_seconds":
                reg.gauge("fedml_slo_round_duration_p95_seconds"),
            "serve_shed_rate": reg.gauge("fedml_slo_serve_shed_ratio"),
            "torn_frame_rate": reg.gauge("fedml_slo_torn_frame_ratio"),
            "quarantine_rate":
                reg.gauge("fedml_slo_quarantine_per_round_ratio"),
            "health_misalignment_ratio":
                reg.gauge("fedml_slo_health_misalignment_ratio"),
            "health_norm_cv_ratio":
                reg.gauge("fedml_slo_health_norm_cv_ratio"),
            "health_starvation_ratio":
                reg.gauge("fedml_slo_health_starvation_ratio"),
            "device_mem_utilization_ratio":
                reg.gauge("fedml_slo_device_mem_utilization_ratio"),
            "serve_queue_utilization_ratio":
                reg.gauge("fedml_slo_serve_queue_utilization_ratio"),
        }
        self._breaches = {name: reg.counter(
            "fedml_slo_breaches_total", slo=name)
            for name in self._gauges}

    @staticmethod
    def _sum_family(counters: dict, family: str) -> float:
        return sum(v for k, v in counters.items() if k.startswith(family))

    def _values(self, snap: dict) -> Dict[str, Optional[float]]:
        counters = snap.get("counters", {})
        hists = snap.get("histograms", {})

        p95 = None
        for series, stats in hists.items():
            if series.startswith(("fedml_round_duration_seconds",
                                  "fedml_async_version_duration_seconds")):
                q = histogram_quantile(stats, 0.95)
                if q is not None:
                    p95 = q if p95 is None else max(p95, q)

        submitted = self._sum_family(counters, "fedml_serve_requests_total")
        # slo_degraded sheds are EXCLUDED from the numerator: they are a
        # CONSEQUENCE of an already-breaching objective (the tier gate
        # shedding best-effort), not fresh evidence of overload.  A shed
        # submit never increments requests_total, so counting them would
        # close a feedback loop — tier-gate sheds inflate shed_rate,
        # which keeps the gate degraded, which sheds more — latching a
        # transient breach into a permanent one at any best-effort mix
        # above threshold/(1+threshold).
        shed = sum(v for k, v in counters.items()
                   if k.startswith("fedml_serve_shed_total")
                   and 'reason="slo_degraded"' not in k)
        shed_rate = (shed / submitted) if submitted else 0.0

        recv = self._sum_family(counters, "fedml_comm_recv_total")
        torn = self._sum_family(counters, "fedml_wire_torn_frames_total")
        torn_rate = (torn / recv) if recv else 0.0

        rounds = sum(h.get("count", 0) for s, h in hists.items()
                     if s.startswith(("fedml_round_duration_seconds",
                                      "fedml_async_version_duration_"
                                      "seconds")))
        quarantines = self._sum_family(
            counters, "fedml_robust_quarantine_events_total")
        quarantine_rate = (quarantines / rounds) if rounds else 0.0

        # drift alarms: the health observatory exports these per round;
        # an absent gauge (health off, or no round closed yet) reads as
        # None — vacuously healthy, never a fabricated zero
        gauges = snap.get("gauges", {})
        health = {name: gauges.get(f"fedml_{name}")
                  for name in ("health_misalignment_ratio",
                               "health_norm_cv_ratio",
                               "health_starvation_ratio")}

        return {"round_duration_p95_seconds": p95,
                "serve_shed_rate": shed_rate,
                "torn_frame_rate": torn_rate,
                "quarantine_rate": quarantine_rate,
                # device observatory: worst-device memory utilization
                # (absent gauge — device obs off, or a backend without
                # allocator limits — reads None: vacuously healthy,
                # never a fabricated zero)
                "device_mem_utilization_ratio":
                    gauges.get("fedml_dev_mem_utilization_ratio"),
                # worst worker across the serve pool (absent gauge — no
                # serving — reads None: vacuously healthy)
                "serve_queue_utilization_ratio": max(
                    (v for k, v in gauges.items() if k.startswith(
                        "fedml_serve_queue_utilization_ratio")),
                    default=None),
                **health}

    def evaluate(self, count_breaches: bool = True) -> Dict[str, dict]:
        values = self._values(self._registry.snapshot())
        out: Dict[str, dict] = {}
        for name, threshold in sorted(self.thresholds.items()):
            value = values.get(name)
            ok = value is None or value <= threshold
            if value is not None:
                self._gauges[name].set(value)
            if not ok and count_breaches:
                self._breaches[name].inc()
            out[name] = {"value": value, "threshold": threshold, "ok": ok}
        return out

    def healthy(self) -> bool:
        return all(v["ok"]
                   for v in self.evaluate(count_breaches=False).values())
