"""Straggler CHAOS: randomized delays and silo deaths against the
cross-silo drop policy and the async (FedBuff) server — liveness and
progress must survive every seed (VERDICT r3 item 7).

The reference's only straggler story is a barrier that hangs until
MPI.Abort (FedAvgServerManager.py:51, server_manager.py:64); these tests
assert the opposite contract: with randomized adversarial timing —
uniform train delays, silos dying mid-federation at random rounds — the
server still closes every round (drop policy) or version (async), never
wedges, and the surviving quorum's updates are the ones aggregated.

Determinism note: each case is seeded; 20 seeds per policy.  One silo is
immortal by construction — with EVERY silo dead no quorum policy can
terminate (that is the abort policy's job, tested in test_comm.py).
"""

import threading
import time

import numpy as np
import pytest

from fedml_tpu.algorithms.cross_silo import (
    FedAvgClientActor, FedAvgServerActor, MsgType)
from fedml_tpu.comm.local import LocalHub
from fedml_tpu.comm.message import Message


def _params_tree(seed=0):
    rng = np.random.RandomState(seed)
    return {"dense": {"kernel": rng.randn(4, 3).astype(np.float32),
                      "bias": rng.randn(3).astype(np.float32)}}


class _ChaoticClientActor(FedAvgClientActor):
    """Trains with a random delay; may die (stop answering SYNC) at a
    pre-drawn round.  Death is silent — exactly a crashed/partitioned
    silo from the server's viewpoint."""

    def __init__(self, node_id, transport, train_fn, rng,
                 max_delay_s: float, death_round):
        super().__init__(node_id, transport, train_fn)
        self._rng = rng
        self._max_delay_s = max_delay_s
        self._death_round = death_round  # None = immortal

    def _on_sync(self, msg):
        round_idx = msg.get(Message.ARG_ROUND)
        if self._death_round is not None and round_idx >= self._death_round:
            return  # dead: swallow the sync, never upload
        time.sleep(float(self._rng.uniform(0.0, self._max_delay_s)))
        super()._on_sync(msg)


def _run_federation(server, actors, timeout_s=30.0):
    threads = [threading.Thread(target=a.run, daemon=True) for a in actors]
    for th in threads:
        th.start()
    server.register_handlers()
    server.start()
    done = threading.Event()

    def _serve():
        server.transport.run()
        done.set()

    st = threading.Thread(target=_serve, daemon=True)
    st.start()
    # LIVENESS: the server loop must terminate on its own
    assert done.wait(timeout_s), "server wedged: FINISH never reached"
    for th in threads:
        th.join(timeout=5)


@pytest.mark.parametrize("seed", range(20))
def test_chaos_drop_policy_survives_delays_and_deaths(seed):
    """4 silos, uniform 0..0.15 s train delays, up to 2 silos dying at
    random rounds: every round still closes under the drop policy, the
    run never aborts, and the aggregate ends exactly at
    init + sum(per-round survivor-mean deltas)."""
    rng = np.random.RandomState(1000 + seed)
    n_silos, n_rounds = 4, 3
    hub = LocalHub()
    t_server = hub.transport(0)
    init = _params_tree(seed)

    # silo i's upload adds (i+1) to every leaf; sample counts equal so the
    # weighted mean of survivors is the plain mean of their deltas
    def train_fn(delta):
        def fn(params, client_idx, round_idx):
            import jax
            return jax.tree.map(lambda v: v + delta, params), 10
        return fn

    deaths = {}  # silo id -> death round
    dying = rng.choice(np.arange(2, n_silos + 1), size=2, replace=False)
    for silo in dying:
        if rng.rand() < 0.7:  # not every chosen silo actually dies
            deaths[int(silo)] = int(rng.randint(0, n_rounds))

    completed = []
    server = FedAvgServerActor(
        t_server, init, client_num_in_total=n_silos,
        client_num_per_round=n_silos, num_rounds=n_rounds,
        on_round_done=lambda r, p: completed.append(r),
        straggler_policy="drop", round_timeout_s=0.4, min_silo_frac=0.2)
    actors = [
        _ChaoticClientActor(
            i, hub.transport(i), train_fn(float(i)),
            np.random.RandomState(seed * 100 + i), max_delay_s=0.15,
            death_round=deaths.get(i))
        for i in range(1, n_silos + 1)]

    _run_federation(server, actors)

    assert not server.aborted
    assert server.round_idx == n_rounds
    assert completed == list(range(n_rounds))
    # progress check: replay the expected aggregate from the server's own
    # drop log (survivors of round r = all silos minus dropped)
    expected = np.asarray(init["dense"]["kernel"], np.float64)
    for r in range(n_rounds):
        dropped = set(server.dropped_silos.get(r, []))
        survivors = [i for i in range(1, n_silos + 1) if i not in dropped]
        assert survivors, "quorum closed a round with zero uploads"
        expected = expected + np.mean([float(i) for i in survivors])
        # a dead silo must actually be in the drop log from its death round
    for silo, death in deaths.items():
        for r in range(death, n_rounds):
            assert silo in server.dropped_silos.get(r, []), \
                f"dead silo {silo} missing from round-{r} drop log"
    np.testing.assert_allclose(
        np.asarray(server.params["dense"]["kernel"], np.float64),
        expected, rtol=1e-5)


@pytest.mark.parametrize("seed", range(20))
def test_chaos_async_server_survives_delays_and_deaths(seed):
    """FedBuff server under chaos: random delays plus up to 1 dead silo
    (of 3, goal 2) — versions keep closing from whoever is alive, FINISH
    arrives, staleness stays plausible."""
    from fedml_tpu.algorithms.async_fl import AsyncFedServerActor

    rng = np.random.RandomState(2000 + seed)
    n_silos, versions, goal = 3, 4, 2
    hub = LocalHub()
    init = _params_tree(seed)

    def train_fn(delta):
        def fn(params, client_idx, round_idx):
            import jax
            return jax.tree.map(lambda v: v + delta, params), 10
        return fn

    death = ({int(rng.randint(2, n_silos + 1)): int(rng.randint(0, 2))}
             if rng.rand() < 0.5 else {})
    server = AsyncFedServerActor(
        hub.transport(0), init, client_num_in_total=8, n_silos=n_silos,
        num_versions=versions, aggregation_goal=goal,
        staleness_exponent=0.5, seed=seed)
    # async clients upload DELTAS (delta_encoder seam); the toy train_fn
    # returns params+delta so encode subtracts the base back out
    from fedml_tpu.algorithms.async_fl import delta_encoder
    actors = [
        _ChaoticClientActor(
            i, hub.transport(i), train_fn(float(i)),
            np.random.RandomState(seed * 77 + i), max_delay_s=0.1,
            death_round=death.get(i))
        for i in range(1, n_silos + 1)]
    for a in actors:
        a.encode_upload = delta_encoder

    _run_federation(server, actors)

    assert server.version == versions
    # consumed = versions*goal; up to n_silos - goal more may sit in the
    # final unconsumed buffer (appended on receipt, before consumption)
    assert versions * goal <= len(server.staleness_seen) \
        <= versions * goal + (n_silos - goal)
    assert all(s >= 0 for s in server.staleness_seen)
    # the aggregate must have moved off init and stayed finite
    k = np.asarray(server.params["dense"]["kernel"])
    assert np.isfinite(k).all()
    assert float(np.abs(k - init["dense"]["kernel"]).max()) > 0.1


@pytest.mark.slow
def test_chaos_real_training_converges_under_drop():
    """End-to-end: 3-silo LR federation on synthetic data with random
    delays and one mid-run death still LEARNS (loss decreases) under the
    drop policy — the convergence half of the chaos contract."""
    import jax
    import jax.numpy as jnp
    from fedml_tpu.data.synthetic import mnist_learnable_twin
    from fedml_tpu.models.linear import LogisticRegression
    from fedml_tpu.trainer.local_sgd import make_local_trainer
    from fedml_tpu.trainer.workload import (ClassificationWorkload,
                                            make_client_optimizer)

    data = mnist_learnable_twin(num_clients=3, class_num=4, dim=16,
                                batch_size=8, noise=0.5, seed=0)
    wl = ClassificationWorkload(LogisticRegression(16, 4), num_classes=4)
    local = make_local_trainer(wl, make_client_optimizer("sgd", 0.3),
                               epochs=2)
    one = jax.tree.map(lambda v: v[0, 0], {k: data.train[k]
                                           for k in ("x", "y", "mask")})
    init = wl.init(jax.random.key(0), one)

    def loss_of(params):
        logits = wl.apply(params, jnp.asarray(data.train["x"][0, 0]))
        import optax
        return float(optax.softmax_cross_entropy_with_integer_labels(
            logits, jnp.asarray(data.train["y"][0, 0])).mean())

    def train_fn(silo):
        def fn(params, client_idx, round_idx):
            batches = jax.tree.map(
                lambda v: jnp.asarray(v[silo - 1]),
                {k: data.train[k] for k in ("x", "y", "mask")})
            new_params, _ = local(params, batches,
                                  jax.random.fold_in(jax.random.key(1),
                                                     round_idx))
            n = int(data.train["num_samples"][silo - 1])
            return new_params, n
        return fn

    hub = LocalHub()
    server = FedAvgServerActor(
        hub.transport(0), init, client_num_in_total=3,
        client_num_per_round=3, num_rounds=6,
        straggler_policy="drop", round_timeout_s=1.0, min_silo_frac=0.3)
    actors = [
        _ChaoticClientActor(i, hub.transport(i), train_fn(i),
                            np.random.RandomState(i), max_delay_s=0.05,
                            death_round=3 if i == 3 else None)
        for i in (1, 2, 3)]
    l0 = loss_of(init)
    _run_federation(server, actors, timeout_s=120.0)

    assert not server.aborted and server.round_idx == 6
    assert all(3 in server.dropped_silos.get(r, []) for r in (3, 4, 5))
    assert loss_of(server.params) < 0.7 * l0
