"""Update compression for the cross-silo wire (WAN bandwidth).

The reference ships updates as JSON float lists (fedavg/utils.py:7-16 —
~4x bloat); our binary codec (comm/message.py) removes the encoding
overhead, and this module removes information redundancy on top of it for
bandwidth-limited silos.  Two classic schemes over the UPDATE (delta to the
global model, which is sparse-able and small-ranged; raw weights are
neither):

* ``topk`` — keep the k largest-|x| entries per leaf (Aji & Heafield 2017
  style sparsification): indices (int32) + values, ~2k/n of the dense
  bytes (each kept entry costs an index word plus a value word).
* ``int8`` — per-leaf symmetric linear quantization to uint8 with an f32
  scale: 4x smaller, max error scale/2.

Both are LOSSY; the cross-silo runner applies them to uploads only (the
down-link broadcast stays exact so silos never drift from the true global
model).  ``ErrorFeedback`` keeps the compressor's residual silo-side and
adds it to the next round's delta (EF-SGD) — cross-round client state
deliberately beyond the reference's stateless-client contract
(FedAVGTrainer re-pointed per round, FedAVGTrainer.py:25-29), so it is
flag-gated in the runner.

Pure numpy on purpose: compression runs host-side at the wire boundary,
never inside a jit.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

Pytree = Any

SCHEMES = ("none", "topk", "int8")


def compress_update(tree: Pytree, scheme: str, topk_frac: float = 0.1):
    """tree -> wire-able payload (still a pytree of arrays, so it rides the
    binary message codec unchanged)."""
    if scheme == "none":
        return {"scheme": "none", "tree": tree}
    import jax
    leaves, treedef = jax.tree.flatten(tree)
    if scheme == "topk":
        comp = []
        for x in leaves:
            x = np.asarray(x)
            if not np.issubdtype(x.dtype, np.floating) or x.size < 16:
                comp.append({"dense": x})
                continue
            _check_finite(x, scheme)
            flat = x.reshape(-1)
            k = max(1, int(round(topk_frac * flat.size)))
            idx = np.argpartition(np.abs(flat), -k)[-k:].astype(np.int32)
            comp.append({"idx": idx, "val": flat[idx],
                         "shape": np.asarray(x.shape, np.int64),
                         "dtype": str(x.dtype)})
        return {"scheme": "topk", "leaves": comp,
                "treedef": _treedef_token(treedef, tree)}
    if scheme == "int8":
        comp = []
        for x in leaves:
            x = np.asarray(x)
            if not np.issubdtype(x.dtype, np.floating) or x.size < 16:
                comp.append({"dense": x})
                continue
            _check_finite(x, scheme)
            amax = float(np.max(np.abs(x)))
            scale = amax / 127.0 if amax > 0 else 1.0
            q = np.clip(np.round(x / scale), -127, 127).astype(np.int8)
            comp.append({"q": q, "scale": np.float32(scale),
                         "dtype": str(x.dtype)})
        return {"scheme": "int8", "leaves": comp,
                "treedef": _treedef_token(treedef, tree)}
    raise ValueError(f"unknown compression scheme {scheme!r}; "
                     f"available: {SCHEMES}")


def decompress_update(payload, like: Pytree) -> Pytree:
    """Inverse of compress_update; ``like`` supplies the tree structure
    (the server always knows the model skeleton)."""
    import jax
    scheme = payload["scheme"]
    if scheme == "none":
        return payload["tree"]
    like_leaves, treedef = jax.tree.flatten(like)
    if payload["treedef"] != _treedef_token(treedef, like):
        raise ValueError(
            "compressed payload tree structure does not match the "
            "receiver's model skeleton — sender/receiver model mismatch")
    out = []
    for d, ref in zip(payload["leaves"], like_leaves):
        if "dense" in d:
            out.append(np.asarray(d["dense"]))
        elif scheme == "topk":
            flat = np.zeros(int(np.prod(d["shape"])), dtype=d["dtype"])
            flat[np.asarray(d["idx"])] = np.asarray(d["val"])
            out.append(flat.reshape(tuple(int(s) for s in d["shape"])))
        else:  # int8
            out.append((np.asarray(d["q"], np.float32)
                        * float(d["scale"])).astype(d["dtype"]))
    return jax.tree.unflatten(treedef, out)


def _check_finite(x, scheme: str) -> None:
    """Fail loudly on NaN/Inf updates (module convention): a non-finite
    amax makes int8 silently quantize the whole leaf to garbage, and topk's
    argpartition over NaN silently picks arbitrary coordinates."""
    if not np.isfinite(x).all():
        raise ValueError(
            f"non-finite values in update leaf (shape {x.shape}); "
            f"refusing to {scheme}-compress a diverged update")


class ErrorFeedback:
    """Per-silo EF-SGD residual carry (Seide'14 / Karimireddy'19), ack-aware.

    The naive update ``residual = delta - sent`` at encode time silently
    loses the SENT part whenever the server drops the upload (straggler
    policy "drop" / round timeout) — the compressed delta was never
    aggregated, yet the silo forgets it.  So the residual update is
    DEFERRED: ``record`` parks (delta, sent) until the next S2C sync
    carries the server's accepted-silo list (Message.ARG_ACCEPTED) and
    ``resolve`` settles it — accepted ⇒ carry delta - sent; dropped ⇒
    carry the FULL delta forward.
    """

    def __init__(self):
        self._residual: Dict[Any, Pytree] = {}
        self._pending: Dict[Any, tuple] = {}

    def apply(self, silo, delta: Pytree) -> Pytree:
        """Add the carried residual to this round's delta."""
        r = self._residual.get(silo)
        if r is None:
            return delta
        import jax
        return jax.tree.map(np.add, delta, r)

    def record(self, silo, delta: Pytree, sent: Pytree) -> None:
        """Park this round's (residual-augmented delta, decoded payload)
        until the server's ack arrives."""
        self._pending[silo] = (delta, sent)

    def resolve(self, silo, accepted) -> None:
        """Settle the parked residual once the next sync reveals whether
        the upload was aggregated.  ``accepted=None`` (a server without the
        ack field, or the INIT sync) assumes accepted — the pre-ack
        behavior."""
        if silo not in self._pending:
            return
        delta, sent = self._pending.pop(silo)
        import jax
        if accepted is None or int(silo) in np.asarray(accepted).astype(
                np.int64).tolist():
            self._residual[silo] = jax.tree.map(np.subtract, delta, sent)
        else:
            self._residual[silo] = delta

    # -- checkpoint surface --------------------------------------------------
    # EF residuals are silo-side CROSS-ROUND state: a checkpoint that
    # saves only (params, round, rng) silently drops them and a resumed
    # --error_feedback run diverges from an uninterrupted one (the lost
    # residual re-loses every coordinate topk dropped).  Both the settled
    # residual AND the parked (delta, sent) pending entry must survive —
    # the pending entry settles on the FIRST post-resume sync's ack.

    def state_dict(self, silos, like: Pytree) -> Dict[str, Any]:
        """Fixed-shape host pytree of the full EF state for ``silos``.
        ``like``: a delta-tree template (the params skeleton); absent
        entries serialize as zeros + a 0 flag, so the same structure
        doubles as the orbax restore template regardless of which silos
        happened to hold state at save time."""
        import jax
        zeros = jax.tree.map(lambda v: np.zeros_like(np.asarray(v)), like)
        host = lambda t: jax.tree.map(np.asarray, t)  # noqa: E731
        out = {}
        for silo in silos:
            r = self._residual.get(silo)
            pend = self._pending.get(silo)
            out[f"s{int(silo)}"] = {
                "residual": host(r) if r is not None else zeros,
                "has_residual": np.asarray(r is not None, np.int8),
                "pending_delta": host(pend[0]) if pend else zeros,
                "pending_sent": host(pend[1]) if pend else zeros,
                "has_pending": np.asarray(pend is not None, np.int8)}
        return out

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        """Inverse of ``state_dict`` (silo keys restore as ints — the
        runner keys apply/record/resolve by int silo id)."""
        for key, d in state.items():
            silo = int(key[1:])
            if int(np.asarray(d["has_residual"])):
                self._residual[silo] = d["residual"]
            if int(np.asarray(d["has_pending"])):
                self._pending[silo] = (d["pending_delta"],
                                       d["pending_sent"])


def _treedef_token(treedef, tree) -> str:
    """A cheap structural fingerprint carried on the wire so a mismatched
    decompress fails loudly instead of mis-zipping leaves."""
    return str(treedef)


def wire_bytes(payload) -> int:
    """Approximate payload size (for tests/metrics): summed array bytes."""
    import jax
    return sum(np.asarray(x).nbytes
               for x in jax.tree.leaves(payload)
               if hasattr(np.asarray(x), "nbytes"))
