"""Cross-silo FedAvg: the reference's distributed message choreography on the
host-edge transport layer.

Reference equivalent: the 5-file MPI pattern of
``fedml_api/distributed/fedavg/`` — FedAvgServerManager.py:18-95 (init
broadcast, receive barrier, aggregate, sync), FedAvgClientManager.py:18-75
(train on init/sync, upload), message_define.py:1-30 (int message types).

On-pod this entire choreography collapses into one jit program
(`fedml_tpu.parallel.cohort`); these actors exist for *true* cross-silo
federation — separate hosts/trust domains over gRPC/DCN — where each silo
trains with its own local jit program and only the global aggregation rides
messages.  Weights travel as binary array frames, not JSON float lists
(the reference's transform_tensor_to_list codec, fedavg/utils.py:7-16).

The "process k plays sampled client i" trick (FedAVGTrainer.update_dataset,
FedAVGTrainer.py:25-29) is preserved: the server sends each silo a
``client_idx`` each round and the silo re-points its local shard.
"""

from __future__ import annotations

import logging
import math
import threading
from typing import Callable, Dict, Optional

import jax
import numpy as np

from fedml_tpu.comm.actors import ClientManager, ServerManager
from fedml_tpu.comm.message import Message
from fedml_tpu.comm.transport import Transport
from fedml_tpu.core.pytree import tree_weighted_mean
from fedml_tpu.core.sampling import sample_clients

log = logging.getLogger(__name__)


class MsgType:
    """Message-type constants (parity: message_define.py:1-30)."""
    S2C_INIT = 1          # MSG_TYPE_S2C_INIT_CONFIG
    S2C_SYNC = 2          # MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT
    C2S_MODEL = 3         # MSG_TYPE_C2S_SEND_MODEL_TO_SERVER
    S2C_FINISH = 4        # shutdown signal (reference uses MPI Abort instead)
    ROUND_TIMEOUT = 5     # server self-message from the straggler timer


# a silo-local trainer: (global_params, client_idx, round_idx) ->
# (new_params, num_samples).  Internally this is expected to be a jit'd
# local-SGD program (fedml_tpu.trainer.local_sgd) over the silo's shard.
SiloTrainFn = Callable[[object, int, int], tuple]


class FedAvgServerActor(ServerManager):
    """Rank-0 aggregator actor (reference FedAvgServerManager.py:18-95)."""

    def __init__(self, transport: Transport, init_params,
                 client_num_in_total: int, client_num_per_round: int,
                 num_rounds: int,
                 on_round_done: Optional[Callable[[int, object], None]] = None,
                 straggler_policy: str = "wait",
                 round_timeout_s: Optional[float] = None,
                 min_silo_frac: float = 0.5,
                 decode_upload: Optional[Callable] = None):
        """Failure handling (SURVEY.md §5.3 — the reference has none: its
        barrier waits forever and its only exit is ``MPI.Abort``,
        server_manager.py:64):

        * ``straggler_policy="wait"`` — reference-parity strict barrier;
          with a timeout set it logs the missing silos and keeps waiting.
        * ``"drop"`` — after ``round_timeout_s``, aggregate the silos that
          DID report, provided at least ``min_silo_frac`` of the cohort
          arrived (else keep waiting); stragglers' late uploads are
          discarded by the round tag.
        * ``"abort"`` — after the timeout, send FINISH to every silo and
          stop (the clean version of the reference's MPI abort).
        """
        super().__init__(0, transport)
        if straggler_policy not in ("wait", "drop", "abort"):
            raise ValueError(f"unknown straggler_policy {straggler_policy!r}")
        self.params = init_params
        self.client_num_in_total = client_num_in_total
        self.client_num_per_round = client_num_per_round
        self.num_rounds = num_rounds
        self.round_idx = 0
        self.on_round_done = on_round_done
        self.straggler_policy = straggler_policy
        self.round_timeout_s = round_timeout_s
        self.min_silo_frac = min_silo_frac
        self.aborted = False
        # optional wire decompression: decode_upload(payload, global_params)
        # -> params (comm/compress.py rides here — uploads compressed, the
        # down-link broadcast stays exact)
        self.decode_upload = decode_upload
        self.dropped_silos: Dict[int, list] = {}  # round -> missing silo ids
        self._received: Dict[int, tuple] = {}
        self._num_silos = 0  # silos contacted this round (= sampled cohort)
        self._timer: Optional[threading.Timer] = None
        # silo ids whose uploads were aggregated last round, sent with the
        # next sync so silos can settle deferred error-feedback residuals
        # (a dropped upload must carry its FULL delta forward)
        self._last_accepted: Optional[np.ndarray] = None

    def register_handlers(self) -> None:
        self.register_handler(MsgType.C2S_MODEL, self._on_model)
        self.register_handler(MsgType.ROUND_TIMEOUT, self._on_timeout)

    # -- round logic ---------------------------------------------------------
    def start(self) -> None:
        """Broadcast initial config (send_init_msg, FedAvgServerManager.py:31-39)."""
        self._broadcast(MsgType.S2C_INIT)

    def _sampled(self) -> np.ndarray:
        # deterministic per-round sampling, parity with
        # FedAVGAggregator.client_sampling:89-97 (np.random.seed(round_idx))
        return sample_clients(self.round_idx, self.client_num_in_total,
                              self.client_num_per_round)

    def _broadcast(self, msg_type) -> None:
        ids = self._sampled()
        # sample_clients caps the cohort at client_num_in_total, so the
        # receive barrier must track the actual cohort size, not the config
        self._num_silos = len(ids)
        host_params = jax.tree.map(np.asarray, self.params)
        extra = ({} if self._last_accepted is None
                 else {Message.ARG_ACCEPTED: self._last_accepted})
        for silo, client_idx in enumerate(ids, start=1):
            self.send(msg_type, silo,
                      **{Message.ARG_MODEL_PARAMS: host_params,
                         Message.ARG_CLIENT_INDEX: int(client_idx),
                         Message.ARG_ROUND: self.round_idx, **extra})
        self._arm_timer()

    # -- straggler timer ----------------------------------------------------
    def _arm_timer(self) -> None:
        if self.round_timeout_s is None:
            return
        self._cancel_timer()
        round_at_arm = self.round_idx
        # the timer thread only ENQUEUES a self-message; all policy logic
        # runs on the transport's event loop, so handler state stays
        # single-threaded (SURVEY.md §5.2)
        self._timer = threading.Timer(
            self.round_timeout_s,
            lambda: self.send(MsgType.ROUND_TIMEOUT, 0,
                              **{Message.ARG_ROUND: round_at_arm}))
        self._timer.daemon = True
        self._timer.start()

    def _cancel_timer(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None

    def _on_timeout(self, msg: Message) -> None:
        if msg.get(Message.ARG_ROUND) != self.round_idx:
            return  # stale timer from an already-completed round
        missing = sorted(set(range(1, self._num_silos + 1))
                         - set(self._received))
        if not missing:
            return
        log.warning("round %d: silos %s have not reported after %.1fs "
                    "(policy=%s)", self.round_idx, missing,
                    self.round_timeout_s, self.straggler_policy)
        if self.straggler_policy == "abort":
            self.aborted = True
            for silo in range(1, self._num_silos + 1):
                self.send(MsgType.S2C_FINISH, silo)
            self.finish()
            return
        quorum = max(1, math.ceil(self.min_silo_frac * self._num_silos))
        if self.straggler_policy == "drop" and len(self._received) >= quorum:
            self.dropped_silos[self.round_idx] = missing
            self._complete_round()
            return
        self._arm_timer()  # wait (or drop below quorum): keep waiting

    def _on_model(self, msg: Message) -> None:
        # stale-round guard: a straggler's upload arriving after its round
        # was closed out (drop policy) must not pollute the next barrier
        upload_round = msg.get(Message.ARG_ROUND)
        if upload_round is not None and upload_round != self.round_idx:
            log.warning("discarding round-%s upload from silo %d (current "
                        "round %d)", upload_round, msg.sender_id,
                        self.round_idx)
            return
        # barrier semantics: wait for every sampled silo
        # (check_whether_all_receive, FedAvgServerManager.py:51)
        upload = msg.get(Message.ARG_MODEL_PARAMS)
        # compression-scheme handshake: a payload with a "scheme" tag is a
        # compressed frame (comm/compress.py) — both mismatch directions
        # would otherwise crash far from the misconfiguration
        is_compressed = isinstance(upload, dict) and "scheme" in upload
        if self.decode_upload is None and is_compressed:
            raise ValueError(
                f"silo {msg.sender_id} sent a compressed upload "
                f"(scheme={upload['scheme']!r}) but the server has no "
                f"--wire_compression configured")
        if self.decode_upload is not None:
            if not is_compressed:
                raise ValueError(
                    f"server expects compressed uploads but silo "
                    f"{msg.sender_id} sent plain parameters; launch silos "
                    f"with the same --wire_compression")
            upload = self.decode_upload(upload, self.params)
        self._received[msg.sender_id] = (
            upload, msg.get(Message.ARG_NUM_SAMPLES))
        if len(self._received) < self._num_silos:
            return
        self._complete_round()

    def _complete_round(self) -> None:
        self._cancel_timer()
        trees = [self._received[s][0] for s in sorted(self._received)]
        weights = np.array([self._received[s][1] for s in sorted(self._received)],
                           dtype=np.float32)
        self._last_accepted = np.asarray(sorted(self._received), np.int32)
        self._received.clear()
        self.params = tree_weighted_mean(trees, weights)
        if self.on_round_done is not None:
            self.on_round_done(self.round_idx, self.params)
        self.round_idx += 1
        if self.round_idx >= self.num_rounds:
            for silo in range(1, self._num_silos + 1):
                self.send(MsgType.S2C_FINISH, silo)
            self.finish()
        else:
            self._broadcast(MsgType.S2C_SYNC)

    def finish(self) -> None:
        self._cancel_timer()
        super().finish()


class FedAvgClientActor(ClientManager):
    """Silo-side trainer actor (reference FedAvgClientManager.py:18-75)."""

    def __init__(self, node_id: int, transport: Transport,
                 train_fn: SiloTrainFn,
                 encode_upload: Optional[Callable] = None,
                 on_accepted: Optional[Callable] = None):
        super().__init__(node_id, transport)
        self.train_fn = train_fn
        # optional wire compression: encode_upload(new_params,
        # global_params) -> payload (comm/compress.py)
        self.encode_upload = encode_upload
        # optional ack hook: on_accepted(accepted_silo_ids | None) fires on
        # every sync BEFORE training, so deferred error-feedback residuals
        # settle (ErrorFeedback.resolve) before the next encode reads them
        self.on_accepted = on_accepted

    def register_handlers(self) -> None:
        self.register_handler(MsgType.S2C_INIT, self._on_sync)
        self.register_handler(MsgType.S2C_SYNC, self._on_sync)
        self.register_handler(MsgType.S2C_FINISH, lambda m: self.finish())

    def _on_sync(self, msg: Message) -> None:
        params = msg.get(Message.ARG_MODEL_PARAMS)
        client_idx = msg.get(Message.ARG_CLIENT_INDEX)
        round_idx = msg.get(Message.ARG_ROUND)
        if self.on_accepted is not None:
            self.on_accepted(msg.get(Message.ARG_ACCEPTED))
        new_params, num_samples = self.train_fn(params, client_idx, round_idx)
        upload = jax.tree.map(np.asarray, new_params)
        if self.encode_upload is not None:
            upload = self.encode_upload(upload, params)
        self.send(MsgType.C2S_MODEL, 0,
                  **{Message.ARG_MODEL_PARAMS: upload,
                     Message.ARG_NUM_SAMPLES: int(num_samples),
                     Message.ARG_ROUND: round_idx})
