"""The sharded streaming fold: `core.stream_agg.StreamingAggregator`'s
state, laid out per `ShardPlan` shard — each device folds its shard of
every arriving upload, and nothing O(model) ever lives on one device.

Duck-type contract: this class speaks the exact `StreamingAggregator`
protocol the live server, the round journal, and the perf observatory
already consume — ``reset`` / ``fold`` / ``fold_wave`` / ``finalize`` /
``state_dict`` / ``load_state_dict`` / ``_cache_size`` / ``count`` /
``weight_total`` / ``reference`` / ``defended`` / ``method`` — so the
round lifecycle in `algorithms/cross_silo.py` is unchanged; only the
wire path (per-shard slices) is new.

Fold math (the parity contract tests/test_shard_spine.py pins):

* **unclipped** — per shard, ``acc_s += u_s * w`` elementwise: the same
  sequential per-element reduction the replicated fold runs, so sharded
  and replicated accumulators agree BIT FOR BIT at any S.
* **clipped** — the clip scale needs the GLOBAL update norm, so it is
  two-phase (arXiv 2004.13336's sharded weight-update discipline): each
  shard computes its slice's partial ``sum((u-g)^2)``, one tiny jit
  combines them into ``min(1, clip/||u-g||)``, and every shard folds
  ``g + (u-g)*scale`` with that scalar.  At S=1 the partial IS the full
  norm computed in the replicated path's exact op order — bit-identical;
  at S>1 the partials sum in shard order instead of leaf order, so the
  scale (and everything after it) agrees to float tolerance, not bits.
* **noise** — sigma>0 draws per shard (`fold_in(key, shard)` past the
  round fold): S=1 reproduces the replicated stream bit-for-bit; S>1
  streams are documented-different (same N(0, sigma) distribution).

Finalize backends: ``fused=False`` is the XLA compose (division + noise
per shard); ``fused=True`` wires `core.pallas_agg.make_fused_shard_finalize`
— clip(at fold) + weighted mean + weak-DP noise complete as ONE Pallas
kernel launch per shard, ``interpret=True`` on CPU.  sigma=0 fused is
bit-identical to the XLA compose for f32 models (same elementwise f32
division); the kernels register with the device observatory so the
compile ledger names them and the MFU gauge finally measures an
accelerator-bound hot loop.

Memory: per shard, O(model/S) accumulator + O(model/S) reference; with
a mesh (``model`` axis), each shard's state is committed to its own
device, so per-DEVICE memory scales ~1/S (BENCH_shard.json measures
exactly this from the live buffers).
"""

from __future__ import annotations

import logging
import time
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.core.stream_agg import zeros_acc_like
from fedml_tpu.obs import telemetry
from fedml_tpu.shard_spine.plan import (ShardPlan, _leaf_key,
                                         _shard_key)

log = logging.getLogger(__name__)


class ShardedStreamingAggregator:
    """O(model/S)-per-shard fold-at-arrival defended-mean aggregation.

    ``plan``: the deterministic layout (`plan.ShardPlan`).  ``mesh``: an
    optional mesh with a ``model`` axis of size S — each shard's fold
    state is then committed to its own device; None keeps everything on
    the default device (same math, the honest 1-chip posture).

    Mean only: order-statistic rules need the per-upload population,
    which a sharded fold deliberately never materializes — they refuse
    loudly here (use ``--agg_mode stream --stream_reservoir`` on the
    replicated path instead).
    """

    def __init__(self, plan: ShardPlan, template, *, kind: str = "params",
                 norm_clip: float = 0.0, noise_std: float = 0.0,
                 seed: int = 0, donate="auto", fused: bool = False,
                 interpret: Optional[bool] = None, mesh=None,
                 sentry=None, device=None):
        if kind != "params":
            raise ValueError(
                f"the sharded spine folds cross-silo params uploads only "
                f"(kind='params'); got kind={kind!r} — the async delta "
                f"path is not sharded")
        if norm_clip < 0 or noise_std < 0:
            raise ValueError(f"norm_clip/noise_std must be >= 0, got "
                             f"{norm_clip}/{noise_std}")
        self.plan = plan
        self.method = "mean"
        self.kind = kind
        self.norm_clip = float(norm_clip)
        self.noise_std = float(noise_std)
        self.seed = int(seed)
        self.fused = bool(fused)
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        self.interpret = bool(interpret)
        self.defended = norm_clip > 0 or noise_std > 0
        self._treedef = jax.tree.structure(template)
        self._devices = plan.shard_devices(mesh) if mesh is not None \
            else None
        if donate == "auto":
            donate = jax.default_backend() != "cpu"
        self._donate = bool(donate)

        S = plan.num_shards
        self._weight_flags = [plan.slice_weight_flags(s) for s in range(S)]
        # per-shard hot jits — each a fresh jax.jit, so the cache-size
        # pin (exactly one entry per shard per family after round 0) and
        # the recompile sentry see THIS aggregator's compiles only
        self._fold_fns = [self._make_fold(s) for s in range(S)]
        self._wave_fns = [self._make_fold_wave(s) for s in range(S)]
        self._sumsq_fns = ([self._make_sumsq(s) for s in range(S)]
                           if norm_clip > 0 else None)
        self._sumsq_wave_fns = ([self._make_sumsq_wave(s)
                                 for s in range(S)]
                                if norm_clip > 0 else None)
        self._scale_fn = jax.jit(self._combine_scale) if norm_clip > 0 \
            else None
        self._wadd_fn = jax.jit(
            lambda ws, w: ws + w,
            donate_argnums=(0,) if self._donate else ())
        self._wadd_wave_fn = jax.jit(
            lambda ws, w: jax.lax.scan(
                lambda c, wi: (c + wi, None), ws, w)[0],
            donate_argnums=(0,) if self._donate else ())
        if fused:
            from fedml_tpu.core.pallas_agg import make_fused_shard_finalize
            self._finalize_fns = [
                make_fused_shard_finalize(
                    noise_std=noise_std, seed=seed, shard_salt=s,
                    interpret=self.interpret)
                for s in range(S)]
        else:
            self._finalize_fns = [self._make_finalize(s) for s in range(S)]
        # the raw jits, kept for the cache probe (device instrumentation
        # wraps the CALLED handles below but forwards _cache_size)
        self._hot_jits = (self._fold_fns + self._wave_fns
                          + self._finalize_fns + [self._wadd_fn,
                                                  self._wadd_wave_fn]
                          + (self._sumsq_fns or [])
                          + (self._sumsq_wave_fns or [])
                          + ([self._scale_fn] if self._scale_fn else []))
        if device is not None:
            fam = "shard_spine[mean]"
            self._fold_fns = [
                device.instrument(f"shard_fold[s{s}]", fn, sentry=sentry,
                                  sentry_name=fam)
                for s, fn in enumerate(self._fold_fns)]
            fin_label = "fused_finalize" if fused else "shard_finalize"
            self._finalize_fns = [
                device.instrument(f"{fin_label}[s{s}]", fn, sentry=sentry,
                                  sentry_name=fam)
                for s, fn in enumerate(self._finalize_fns)]
        if sentry is not None:
            sentry.register("shard_spine[mean]", self)

        reg = telemetry.get_registry()
        self._c_folds = reg.counter("fedml_stream_folds_total")
        self._c_slices = reg.counter("fedml_shard_slices_total")
        self._c_fused = reg.counter("fedml_shard_fused_launches_total")
        self._g_acc_bytes = reg.gauge("fedml_shard_acc_bytes")
        self._h_finalize = reg.histogram("fedml_shard_finalize_seconds")

        # per-round state: one slice dict per shard
        self._reference: Optional[List[dict]] = None
        self._acc: Optional[List[dict]] = None
        self._wsum = None
        self.count = 0
        self.weight_total = 0.0

    # -- jit factories -------------------------------------------------------
    def _make_fold(self, shard: int):
        flags = self._weight_flags[shard]
        clip = self.norm_clip

        def _fold(acc, upload, weight, reference, scale):
            out = {}
            for k, flag in zip(sorted(acc), flags):
                a, u, g = acc[k], upload[k], reference[k]
                if clip > 0 and flag:
                    # clip_update's exact per-leaf apply, with the
                    # (two-phase) global scale passed in as a scalar
                    u = g + (u - g) * scale.astype(u.dtype)
                out[k] = a + u.astype(a.dtype) * weight.astype(a.dtype)
            return out

        return jax.jit(_fold,
                       donate_argnums=(0,) if self._donate else ())

    def _make_fold_wave(self, shard: int):
        flags = self._weight_flags[shard]
        clip = self.norm_clip

        def _fold_wave(acc, stacked, weights, reference, scales):
            def body(carry, xs):
                upload, w, s = xs
                out = {}
                for k, flag in zip(sorted(carry), flags):
                    a, u, g = carry[k], upload[k], reference[k]
                    if clip > 0 and flag:
                        u = g + (u - g) * s.astype(u.dtype)
                    out[k] = a + u.astype(a.dtype) * w.astype(a.dtype)
                return out, None

            acc, _ = jax.lax.scan(body, acc, (stacked, weights, scales))
            return acc

        return jax.jit(_fold_wave,
                       donate_argnums=(0,) if self._donate else ())

    @staticmethod
    def _slice_sumsq(upload, reference, flags):
        """_masked_global_norm's exact op order over one shard's
        pieces: diff in the leaf's own dtype, squared in f32, summed
        sequentially in slice-key order.  ONE definition — the
        per-upload and wave clip norms must never desynchronize."""
        total = 0.0
        for k, flag in zip(sorted(upload), flags):
            if flag:
                d = upload[k] - reference[k]
                total = total + jnp.sum(jnp.square(d.astype(jnp.float32)))
        return jnp.asarray(total, jnp.float32)

    def _make_sumsq(self, shard: int):
        flags = self._weight_flags[shard]
        return jax.jit(lambda upload, reference: self._slice_sumsq(
            upload, reference, flags))

    def _make_sumsq_wave(self, shard: int):
        flags = self._weight_flags[shard]

        def _sumsq_wave(stacked, reference):
            return jax.vmap(lambda u: self._slice_sumsq(
                u, reference, flags))(stacked)

        return jax.jit(_sumsq_wave)

    def _combine_scale(self, partials):
        # clip_update's scale formula over the summed shard partials
        total = 0.0
        for p in partials:
            total = total + p
        norm = jnp.sqrt(total)
        return jnp.minimum(1.0, self.norm_clip
                           / jnp.maximum(norm, 1e-12))

    def _make_finalize(self, shard: int):
        noise = self.noise_std
        seed = self.seed
        S = self.plan.num_shards

        def _finalize(acc, wsum, reference, step):
            out = {k: (acc[k] / wsum.astype(acc[k].dtype)).astype(
                jnp.asarray(reference[k]).dtype) for k in sorted(acc)}
            if noise > 0:
                from fedml_tpu.core.robust import add_gaussian_noise
                key = jax.random.fold_in(jax.random.key(seed),
                                         jnp.asarray(step, jnp.uint32))
                if S > 1:
                    # decorrelate the per-shard streams; at S=1 the key
                    # chain (and the per-leaf split in
                    # add_gaussian_noise) reproduces the replicated
                    # path's draw bit for bit
                    key = jax.random.fold_in(key, jnp.uint32(shard))
                out = add_gaussian_noise(out, key, noise)
            return out

        return jax.jit(_finalize)

    # -- recompile-sentry probe ----------------------------------------------
    def _cache_size(self) -> int:
        total = 0
        for fn in self._hot_jits:
            total += int(fn._cache_size())
        return total

    # -- round lifecycle -----------------------------------------------------
    @property
    def reference(self):
        return self._reference

    def _place(self, shard: int, slice_body: dict) -> dict:
        """Commit one shard's pieces to its device (consistent committed
        placement = one jit cache entry per shard; the PR 13 lesson)."""
        if self._devices is None:
            return {k: jnp.asarray(v) for k, v in slice_body.items()}
        dev = self._devices[shard]
        return {k: jax.device_put(v, dev) for k, v in slice_body.items()}

    def _split_body(self, tree_or_leaves) -> List[dict]:
        """Full tree (or ordered leaf list) -> per-shard slice BODIES
        (the inner ``{leaf_key: piece}`` dicts)."""
        leaves = (tree_or_leaves if isinstance(tree_or_leaves, list)
                  else [np.asarray(x)
                        for x in jax.tree.leaves(tree_or_leaves)])
        slices = self.plan.split_leaves(leaves)
        return [sl[_shard_key(s)] for s, sl in enumerate(slices)]

    def reset(self, reference) -> None:
        host = jax.tree.map(np.asarray, reference)
        self._reference = [self._place(s, body) for s, body in
                           enumerate(self._split_body(host))]
        self._acc = None
        self._wsum = None
        self.count = 0
        self.weight_total = 0.0

    def _ensure_acc(self) -> None:
        if self._acc is not None:
            return
        self._acc = [self._place(s, zeros_acc_like(ref))
                     for s, ref in enumerate(self._reference)]
        self._wsum = jnp.float32(0.0)
        self._g_acc_bytes.set(max(
            sum(int(np.prod(v.shape or (1,))
                    * jnp.dtype(v.dtype).itemsize)
                for v in body.values())
            for body in self._acc))

    def _slice_bodies(self, slices: Sequence[dict]) -> List[dict]:
        """Validate + unwrap wire slices (``{"s<idx>": body}``) into
        per-shard bodies; plain bodies pass through."""
        S = self.plan.num_shards
        if len(slices) != S:
            raise ValueError(f"fold_slices needs {S} slices, got "
                             f"{len(slices)}")
        out = []
        for s, sl in enumerate(slices):
            body = sl.get(_shard_key(s)) if isinstance(sl, dict) \
                and _shard_key(s) in sl else sl
            out.append(body)
        return out

    def fold_slices(self, slices: Sequence[dict], weight) -> None:
        """Fold one ADMITTED upload, delivered as its S shard slices, at
        arrival.  Per shard: O(model/S) work on that shard's device."""
        if self._reference is None:
            raise RuntimeError("fold_slices() before reset(): the "
                               "round's clip reference is not set")
        bodies = [self._place(s, b) for s, b in
                  enumerate(self._slice_bodies(slices))]
        self._ensure_acc()
        w = np.float32(weight)
        scale = np.float32(1.0)
        if self.norm_clip > 0:
            # partials come back committed to their shards' devices;
            # combine from HOST scalars so the tiny scale jit never
            # sees mixed placements, and hand each shard's fold the
            # scale as an uncommitted host scalar for the same reason
            partials = tuple(
                np.asarray(self._sumsq_fns[s](bodies[s],
                                              self._reference[s]))
                for s in range(self.plan.num_shards))
            scale = np.asarray(self._scale_fn(partials))
        for s in range(self.plan.num_shards):
            self._acc[s] = self._fold_fns[s](
                self._acc[s], bodies[s], w, self._reference[s], scale)
        self._wsum = self._wadd_fn(self._wsum, jnp.float32(w))
        self._c_folds.inc()
        self._c_slices.inc(self.plan.num_shards)
        self.count += 1
        self.weight_total += float(weight)

    def fold(self, upload, weight) -> None:
        """`StreamingAggregator.fold` twin: a full-tree upload is split
        host-side and folded per shard (tests, and any caller that never
        saw per-shard wire slices)."""
        self.fold_slices(
            [{_shard_key(s): b} for s, b in
             enumerate(self._split_body(upload))], weight)

    def fold_wave(self, stacked, weights) -> None:
        """Fold one compiled wave's ``[wave, ...]`` stacked updates: the
        wave stack is split per shard (slot axis intact) and each shard
        runs the sequential per-slot scan — the replicated
        `fold_wave`'s exact fold order, so wave-chunked == per-upload
        folds per shard.  Weight-0 padded slots contribute an exact
        ``+0.0``."""
        if self._reference is None:
            raise RuntimeError("fold_wave() before reset(): the round's "
                               "clip reference is not set")
        w_host = np.asarray(weights, np.float32)
        wave = int(w_host.shape[0])
        leaves = [np.asarray(x) for x in jax.tree.leaves(stacked)]
        bodies = [self._place(s, b) for s, b in enumerate(
            self._split_body_stacked(leaves, wave))]
        self._ensure_acc()
        w_dev = w_host  # uncommitted host arrays follow each shard's
        #                 committed placement inside the per-shard jits
        if self.norm_clip > 0:
            partials = tuple(
                np.asarray(self._sumsq_wave_fns[s](bodies[s],
                                                   self._reference[s]))
                for s in range(self.plan.num_shards))
            scales = np.asarray(self._scale_fn(partials))
        else:
            scales = np.ones((wave,), np.float32)
        for s in range(self.plan.num_shards):
            self._acc[s] = self._wave_fns[s](
                self._acc[s], bodies[s], w_dev, self._reference[s],
                scales)
        self._wsum = self._wadd_wave_fn(self._wsum, w_dev)
        live = int((w_host > 0).sum())
        self._c_folds.inc(live)
        self._c_slices.inc(live * self.plan.num_shards)
        self.count += live
        for w in w_host:   # the per-upload path's exact host arithmetic
            self.weight_total += float(w)

    def _split_body_stacked(self, leaves: List[np.ndarray],
                            wave: int) -> List[dict]:
        """Split ``[wave, ...]``-stacked leaves per shard: the plan's
        split dim shifts by the slot axis."""
        S = self.plan.num_shards
        out: List[dict] = [{} for _ in range(S)]
        if len(leaves) != len(self.plan.leaves):
            raise ValueError(
                f"shard plan covers {len(self.plan.leaves)} leaves but "
                f"the wave stack has {len(leaves)}")
        for lp, arr in zip(self.plan.leaves, leaves):
            if tuple(arr.shape) != (wave,) + lp.shape:
                raise ValueError(
                    f"wave leaf {lp.index} ({lp.path}) has shape "
                    f"{arr.shape}; expected {(wave,) + lp.shape}")
            key = _leaf_key(lp.index)
            if lp.mode == "split":
                n = lp.shape[lp.dim] // S
                for s in range(S):
                    idx = [slice(None)] * arr.ndim
                    idx[lp.dim + 1] = slice(s * n, (s + 1) * n)
                    out[s][key] = arr[tuple(idx)]
            else:
                out[lp.owner][key] = arr
        return out

    def finalize(self, step):
        """Close the round: per shard, ``acc/wsum (+ noise)`` — one XLA
        program or ONE fused Pallas launch per shard — then an exact
        host join back to the full tree."""
        if self.count == 0:
            raise RuntimeError("finalize() with no folded uploads; the "
                               "caller must skip aggregation on an "
                               "empty round")
        t0 = time.perf_counter()
        out_slices = []
        # host scalars: every shard's finalize jit sees its own
        # committed acc/reference plus uncommitted wsum/step (a
        # committed default-device wsum would mix placements)
        wsum = np.asarray(self._wsum, np.float32)
        step32 = np.int32(step)
        for s in range(self.plan.num_shards):
            out = self._finalize_fns[s](self._acc[s], wsum,
                                        self._reference[s], step32)
            if self.fused:
                self._c_fused.inc()
            out_slices.append({_shard_key(s): out})
        self._acc = None
        self._wsum = None
        host_slices = [
            {_shard_key(s): {k: np.asarray(v)
                       for k, v in sl[_shard_key(s)].items()}}
            for s, sl in enumerate(out_slices)]
        leaves = self.plan.join_slices(host_slices)
        self._h_finalize.observe(time.perf_counter() - t0)
        return jax.tree.unflatten(self._treedef, leaves)

    # -- crash consistency (utils/journal.py) --------------------------------
    def state_dict(self, include_reference: bool = False) -> dict:
        """`StreamingAggregator.state_dict` twin: the SHARDED
        accumulator as one flat host leaf list (shard-major, slice-key
        order), plus the plan fingerprint so a resume refuses to restore
        into a different layout.  Bit-exact: pieces round-trip through
        numpy in their own acc dtype, ``wsum`` stays f32."""
        if include_reference:
            raise ValueError("the sharded spine does not snapshot the "
                             "reference (edge actors are not sharded)")
        acc = None
        if self._acc is not None:
            acc = []
            for body in self._acc:
                for k in sorted(body):
                    acc.append(np.asarray(body[k]))
        return {
            "acc": acc,
            "wsum": (np.float32(0.0) if self._wsum is None
                     else np.asarray(self._wsum, np.float32)[()]),
            "count": int(self.count),
            "weight_total": float(self.weight_total),
            "shard_fp": int(self.plan.fingerprint())}

    def load_state_dict(self, state: dict) -> None:
        if self._reference is None:
            raise RuntimeError("load_state_dict before reset(): the "
                               "round's clip reference is not set")
        snap_fp = state.get("shard_fp")
        if snap_fp is not None and int(snap_fp) != \
                int(self.plan.fingerprint()):
            raise ValueError(
                "journal snapshot was taken under a DIFFERENT shard "
                "plan (fingerprint mismatch — --model_shards or the "
                "model changed since the crash); restoring it would "
                "fold state into the wrong slots")
        if snap_fp is None and state.get("acc") is not None:
            raise ValueError(
                "journal snapshot carries no shard-plan fingerprint "
                "(it was taken by the replicated fold); the sharded "
                "spine refuses to restore it")
        if state.get("acc") is not None:
            flat = [np.asarray(a) for a in state["acc"]]
            pos = 0
            acc = []
            for s, ref in enumerate(self._reference):
                body = {}
                for k in sorted(ref):
                    body[k] = flat[pos]
                    pos += 1
                acc.append(self._place(s, body))
            if pos != len(flat):
                raise ValueError(
                    f"snapshot holds {len(flat)} accumulator pieces but "
                    f"the plan expects {pos}")
            self._acc = acc
            self._wsum = jnp.float32(state["wsum"])
        self.count = int(state["count"])
        self.weight_total = float(state["weight_total"])
