"""The reference's key correctness oracle (CI-script-fedavg.sh:41-49):

    full batch + E=1 + full participation  =>  FedAvg == centralized

exactly (one aggregated FedAvg step equals one pooled-gradient step), plus
cohort-engine invariants: vmap cohort == sequential clients, single-chip ==
8-device shard_map, padded dummy clients are no-ops."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.algorithms import FedAvg, FedAvgConfig, CentralizedTrainer
from fedml_tpu.data.stacking import (
    stack_client_data, batch_global, gather_cohort, FederatedData,
)
from fedml_tpu.models import LogisticRegression
from fedml_tpu.parallel.cohort import make_cohort_step
from fedml_tpu.parallel.mesh import make_mesh
from fedml_tpu.trainer.workload import ClassificationWorkload, make_client_optimizer
from fedml_tpu.trainer.local_sgd import make_local_trainer


def _synthetic_clients(n_clients=8, dim=12, classes=4, seed=0, min_n=6, max_n=20):
    """Linearly-separable-ish synthetic classification data, ragged sizes."""
    rng = np.random.RandomState(seed)
    W = rng.randn(dim, classes)
    xs, ys = [], []
    for _ in range(n_clients):
        n = rng.randint(min_n, max_n + 1)
        x = rng.randn(n, dim).astype(np.float32)
        y = np.argmax(x @ W + 0.1 * rng.randn(n, classes), axis=1).astype(np.int32)
        xs.append(x)
        ys.append(y)
    return xs, ys


def _make_fed_data(xs, ys, batch_size, classes=4):
    train = stack_client_data(xs, ys, batch_size)
    return FederatedData(client_num=len(xs), class_num=classes, train=train,
                         test=train)


@pytest.fixture(scope="module")
def workload():
    model = LogisticRegression(input_dim=12, output_dim=4)
    return ClassificationWorkload(model, num_classes=4, grad_clip_norm=None)


def test_fullbatch_fedavg_equals_centralized(workload):
    xs, ys = _synthetic_clients()
    data = _make_fed_data(xs, ys, batch_size=32)  # >= max client size: 1 batch
    cfg = FedAvgConfig(comm_round=3, client_num_per_round=8, epochs=1,
                       batch_size=32, lr=0.5, frequency_of_the_test=100)
    fed = FedAvg(workload, data, cfg)
    params0 = fed.init_params(jax.random.key(7))
    fed_params = fed.run(params=jax.tree.map(jnp.copy, params0))

    pooled_x = np.concatenate(xs)
    pooled_y = np.concatenate(ys)
    central = CentralizedTrainer(workload, lr=0.5)
    central_data = batch_global(pooled_x, pooled_y, batch_size=len(pooled_x))
    central_params = central.train_rounds(params0, central_data, rounds=3)

    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5),
        fed_params, central_params)

    fed_acc = fed.evaluate_global(fed_params)["train_acc"]
    cen_acc = central.metrics(central_params,
                              {k: central_data[k] for k in ("x", "y", "mask")})
    assert abs(fed_acc - cen_acc["acc"]) < 1e-3  # the CI script's 3-decimals


def test_vmap_cohort_equals_sequential_clients(workload):
    """One vmap'd cohort step == training each client separately then
    weighted-averaging (the reference's sequential simulator semantics)."""
    xs, ys = _synthetic_clients(n_clients=4)
    train = stack_client_data(xs, ys, batch_size=5)
    opt = make_client_optimizer("sgd", 0.1)
    local = make_local_trainer(workload, opt, epochs=2)
    step = make_cohort_step(local)

    params = workload.init(jax.random.key(0),
                           jax.tree.map(lambda v: v[0, 0],
                                        {k: train[k] for k in ("x", "y", "mask")}))
    rng = jax.random.key(3)
    cohort = {k: jnp.asarray(v) for k, v in train.items()}
    agg, _ = step(params, cohort, rng)

    # sequential: same per-client rng assignment as the cohort engine
    rngs = [jax.random.fold_in(rng, i) for i in range(4)]
    client_params = []
    for c in range(4):
        cdata = {k: jnp.asarray(train[k][c]) for k in ("x", "y", "mask")}
        p, _ = local(params, cdata, rngs[c])
        client_params.append(p)
    from fedml_tpu.core import tree_weighted_mean
    want = tree_weighted_mean(client_params, jnp.asarray(train["num_samples"]))
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5),
                 agg, want)


def test_scan_client_axis_equals_vmap(workload):
    """client_axis='scan' (sequential clients, dense convs — the MXU
    alternative to vmap's grouped-conv lowering, bench R56 grid) must
    produce the exact same round as the vmapped engine: same stacked
    outputs, same aggregate, same per-client rng streams."""
    xs, ys = _synthetic_clients(n_clients=4)
    train = stack_client_data(xs, ys, batch_size=5)
    opt = make_client_optimizer("sgd", 0.1)
    local = make_local_trainer(workload, opt, epochs=2)
    params = workload.init(jax.random.key(0),
                           jax.tree.map(lambda v: v[0, 0],
                                        {k: train[k] for k in ("x", "y", "mask")}))
    cohort = {k: jnp.asarray(v) for k, v in train.items()}
    rng = jax.random.key(3)
    agg_v, m_v = make_cohort_step(local)(params, cohort, rng)
    agg_s, m_s = make_cohort_step(local, client_axis="scan")(
        params, cohort, rng)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        a, b, rtol=1e-5, atol=1e-6), agg_v, agg_s)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        a, b, rtol=1e-5, atol=1e-6), m_v, m_s)
    with pytest.raises(ValueError, match="client_axis"):
        make_cohort_step(local, client_axis="pmap")(params, cohort, rng)


def test_chunked_global_eval_equals_full_sweep(workload):
    """evaluate_global with eval_chunk_clients set must equal the
    all-clients vmap exactly (summed metric dicts; zero-padded tail
    chunk contributes nothing) — the 342k-client corpora path, where the
    one-shot vmap would materialize [C, S, B, ...] activations."""
    import dataclasses
    from fedml_tpu.algorithms.fedavg import FedAvg, FedAvgConfig

    xs, ys = _synthetic_clients(n_clients=7)
    from fedml_tpu.data.stacking import FederatedData
    data = FederatedData(client_num=7, class_num=3,
                         train=stack_client_data(xs, ys, batch_size=5))
    base = FedAvgConfig(comm_round=1, client_num_per_round=3, batch_size=5,
                        frequency_of_the_test=10**9)
    full = FedAvg(workload, data, dataclasses.replace(
        base, eval_chunk_clients=0))
    params = full.run()
    chunked = FedAvg(workload, data, dataclasses.replace(
        base, eval_chunk_clients=2))
    a, b = full.evaluate_global(params), chunked.evaluate_global(params)
    assert a.keys() == b.keys() and a
    for k in a:
        np.testing.assert_allclose(a[k], b[k], rtol=1e-6)

    # sharded eval chunks too (each chunk rides the shard_map eval jit)
    from fedml_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(client_axis=4, devices=jax.devices("cpu")[:4])
    sharded = FedAvg(workload, data, dataclasses.replace(
        base, client_num_per_round=4, eval_chunk_clients=2), mesh=mesh)
    c = sharded.evaluate_global(params)
    for k in a:
        np.testing.assert_allclose(a[k], c[k], rtol=1e-6)


def test_sharded_cohort_equals_single_chip(workload, devices):
    """8-device shard_map cohort == single-chip vmap cohort."""
    xs, ys = _synthetic_clients(n_clients=8)
    train = stack_client_data(xs, ys, batch_size=5)
    opt = make_client_optimizer("sgd", 0.1)
    local = make_local_trainer(workload, opt, epochs=1)

    params = workload.init(jax.random.key(0),
                           jax.tree.map(lambda v: v[0, 0],
                                        {k: train[k] for k in ("x", "y", "mask")}))
    cohort = {k: jnp.asarray(v) for k, v in train.items()}
    rng = jax.random.key(5)

    single = make_cohort_step(local)
    mesh = make_mesh(devices=devices, client_axis=8, model_axis=1)
    sharded = make_cohort_step(local, mesh=mesh)

    got_single, _ = single(params, cohort, rng)
    got_sharded, _ = sharded(params, cohort, rng)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5),
                 got_single, got_sharded)


def test_padded_dummy_clients_are_noops(workload):
    """gather_cohort pad_to: dummy clients contribute nothing."""
    xs, ys = _synthetic_clients(n_clients=5)
    train = stack_client_data(xs, ys, batch_size=5)
    opt = make_client_optimizer("sgd", 0.1)
    local = make_local_trainer(workload, opt, epochs=1)
    step = make_cohort_step(local)

    params = workload.init(jax.random.key(0),
                           jax.tree.map(lambda v: v[0, 0],
                                        {k: train[k] for k in ("x", "y", "mask")}))
    rng = jax.random.key(1)
    exact = gather_cohort(train, [1, 3])
    padded = gather_cohort(train, [1, 3], pad_to=4)
    got_exact, _ = step(params, exact, rng)
    # padded run uses a different per-client rng split, but SGD on identical
    # data is rng-free here (no dropout), so results must match
    got_padded, _ = step(params, padded, rng)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5),
                 got_exact, got_padded)


def test_adam_optimizer_path(workload):
    """Adam (amsgrad) client optimizer runs and fully-padded batches do not
    drift parameters."""
    xs, ys = _synthetic_clients(n_clients=2, min_n=3, max_n=3)
    # force steps where client 1 has padded batches: client 0 gets 9 samples
    xs[0] = np.random.RandomState(1).randn(9, 12).astype(np.float32)
    ys[0] = np.zeros(9, np.int32)
    train = stack_client_data(xs, ys, batch_size=3)
    assert train["x"].shape[1] == 3  # 3 steps; client 1 has 2 fully-padded
    opt = make_client_optimizer("adam", 1e-2, wd=1e-3)
    local = make_local_trainer(workload, opt, epochs=1)

    params = workload.init(jax.random.key(0),
                           jax.tree.map(lambda v: v[0, 0],
                                        {k: train[k] for k in ("x", "y", "mask")}))
    cdata1 = {k: jnp.asarray(train[k][1]) for k in ("x", "y", "mask")}
    p1, _ = local(params, cdata1, jax.random.key(2))
    # only the first of 3 steps has data; params must still move
    assert float(jax.numpy.abs(p1["Dense_0"]["kernel"] - params["Dense_0"]["kernel"]).max()) > 0

    # a client with NO data at all: params must come back unchanged
    empty = jax.tree.map(jnp.zeros_like, cdata1)
    p_empty, _ = local(params, empty, jax.random.key(3))
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, rtol=0, atol=0),
                 p_empty, params)


def test_device_round_equals_host_gather():
    """The HBM-resident in-jit gather round (make_device_round) must equal
    the host-gather cohort step bit-for-bit, including weight-0 padding."""
    import jax
    import jax.numpy as jnp
    from fedml_tpu.core.sampling import sample_clients
    from fedml_tpu.data.stacking import gather_cohort, stack_client_data
    from fedml_tpu.models import LogisticRegression
    from fedml_tpu.parallel.cohort import make_cohort_step, make_device_round
    from fedml_tpu.trainer.local_sgd import make_local_trainer
    from fedml_tpu.trainer.workload import (ClassificationWorkload,
                                            make_client_optimizer)

    rng = np.random.RandomState(3)
    xs = [rng.randn(rng.randint(4, 12), 6).astype(np.float32)
          for _ in range(9)]
    ys = [rng.randint(0, 3, len(x)).astype(np.int32) for x in xs]
    stacked = stack_client_data(xs, ys, batch_size=4)
    wl = ClassificationWorkload(LogisticRegression(6, 3), num_classes=3,
                                grad_clip_norm=None)
    local = make_local_trainer(wl, make_client_optimizer("sgd", 0.1), 1)
    step = make_cohort_step(local)
    m = 4
    round_fn = make_device_round(local, m)
    params = wl.init(jax.random.key(0), jax.tree.map(
        lambda v: jnp.asarray(v[0, 0]),
        {k: stacked[k] for k in ("x", "y", "mask")}))
    stacked_dev = {k: jnp.asarray(v) for k, v in stacked.items()}

    for rnd in range(3):
        ids = sample_clients(rnd, 9, m)[:3]  # 3 live + 1 padded slot
        key = jax.random.key(rnd)
        host_cohort = gather_cohort(stacked, ids, pad_to=m)
        p_host, _ = step(params, host_cohort, key)
        padded_ids = np.zeros(m, np.int32)
        padded_ids[:3] = ids
        live = jnp.asarray([1.0, 1.0, 1.0, 0.0])
        p_dev, _ = round_fn(params, stacked_dev, jnp.asarray(padded_ids),
                            live, key)
        for a, b in zip(jax.tree.leaves(p_host), jax.tree.leaves(p_dev)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        params = p_dev


def test_fedavg_device_path_matches_host_path():
    """FedAvg.run with the device-resident fast path == forced host gather."""
    from fedml_tpu.algorithms.fedavg import FedAvg, FedAvgConfig
    from fedml_tpu.data.synthetic import synthetic_federated_dataset
    from fedml_tpu.models import LogisticRegression
    from fedml_tpu.trainer.workload import ClassificationWorkload

    data = synthetic_federated_dataset(num_clients=9, samples_per_client=10,
                                       sample_shape=(6,), class_num=3,
                                       batch_size=4)
    wl = ClassificationWorkload(LogisticRegression(6, 3), num_classes=3,
                                grad_clip_norm=None)
    cfg = FedAvgConfig(comm_round=3, client_num_per_round=4, batch_size=4,
                       lr=0.1, frequency_of_the_test=100, seed=0)
    fast_algo = FedAvg(wl, data, cfg)
    fast = fast_algo.run()
    assert fast_algo._train_dev is not None  # fast path actually engaged
    slow_algo = FedAvg(wl, data, cfg)
    slow_algo._stage_train_on_device = lambda *a, **k: False  # force host
    slow = slow_algo.run()
    for a, b in zip(jax.tree.leaves(fast), jax.tree.leaves(slow)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_scanned_rounds_path_matches_loop_cadence(workload):
    """rounds_per_dispatch>1 (lax.scan K rounds per dispatch) must hit the
    same eval rounds and reach the same quality as the host-loop path (rng
    schedules differ by design, so trajectories are compared statistically:
    same cadence, both learn)."""
    xs, ys = _synthetic_clients(n_clients=12, seed=3)
    data = _make_fed_data(xs, ys, batch_size=8)
    mk = lambda rpd: FedAvgConfig(
        comm_round=33, client_num_per_round=4, epochs=1, batch_size=8,
        lr=0.5, frequency_of_the_test=16, seed=5, rounds_per_dispatch=rpd)
    loop = FedAvg(workload, data, mk(1))
    scan = FedAvg(workload, data, mk(4))
    p0 = loop.init_params(jax.random.key(2))
    loop.run(params=jax.tree.map(jnp.copy, p0), rng=jax.random.key(3))
    scan.run(params=jax.tree.map(jnp.copy, p0), rng=jax.random.key(3))
    assert [h["round"] for h in loop.history] == \
           [h["round"] for h in scan.history] == [0, 16, 32]
    acc_loop = loop.history[-1]["train_acc"]
    acc_scan = scan.history[-1]["train_acc"]
    assert acc_scan > 0.6 and abs(acc_scan - acc_loop) < 0.2, \
        (acc_loop, acc_scan)


def test_scanned_rounds_same_ids_as_loop(workload, monkeypatch):
    """The scanned path must feed each absolute round the same cohort ids
    the host loop would (sample_clients(round) parity) — only the rng
    schedule differs.  Captured by intercepting the rounds_fn."""
    from fedml_tpu.core.sampling import sample_clients
    import fedml_tpu.parallel.cohort as cohort_mod

    captured = []
    real_make = cohort_mod.make_scanned_rounds

    def spy_make(local_train, m, **kw):
        fn = real_make(local_train, m, **kw)

        def wrapped(params, stacked, ids, live, rng):
            captured.append((np.asarray(ids), np.asarray(live)))
            return fn(params, stacked, ids, live, rng)
        return wrapped

    monkeypatch.setattr(cohort_mod, "make_scanned_rounds", spy_make)
    xs, ys = _synthetic_clients(n_clients=12, seed=3)
    data = _make_fed_data(xs, ys, batch_size=8)
    algo = FedAvg(workload, data, FedAvgConfig(
        comm_round=7, client_num_per_round=4, epochs=1, batch_size=8,
        lr=0.3, frequency_of_the_test=3, seed=5, rounds_per_dispatch=3))
    algo.run(rng=jax.random.key(0))
    flat_ids = np.concatenate([ids for ids, _ in captured])
    assert flat_ids.shape == (7, 4)
    for r in range(7):
        expect = sample_clients(r, 12, 4)
        np.testing.assert_array_equal(flat_ids[r, :len(expect)], expect)


def test_bf16_compute_dtype_mixed_precision():
    """compute_dtype=bfloat16: master params stay f32, training still
    learns, and the trajectory stays close to the f32 run at small lr (the
    TPU mixed-precision mode — f32 CE, bf16 conv/matmul)."""
    import flax.linen as nn

    class _Linear(nn.Module):
        # un-squashed logits: the reference LR's sigmoid caps logits in
        # [0, 1], where bf16's ~8-bit mantissa flattens class margins
        @nn.compact
        def __call__(self, x, train=False):
            return nn.Dense(4)(x.reshape((x.shape[0], -1)))

    xs, ys = _synthetic_clients(n_clients=6, seed=8)
    data = _make_fed_data(xs, ys, batch_size=32)
    cfg = FedAvgConfig(comm_round=20, client_num_per_round=6, epochs=1,
                       batch_size=32, lr=0.3, frequency_of_the_test=100)
    runs = {}
    for name, dt in (("f32", None), ("bf16", jnp.bfloat16)):
        wl = ClassificationWorkload(_Linear(), num_classes=4,
                                    grad_clip_norm=None, compute_dtype=dt)
        algo = FedAvg(wl, data, cfg)
        p0 = algo.init_params(jax.random.key(4))
        p = algo.run(params=jax.tree.map(jnp.copy, p0),
                     rng=jax.random.key(5))
        assert all(l.dtype == jnp.float32 for l in jax.tree.leaves(p))
        runs[name] = (p, algo.evaluate_global(p)["train_acc"])
    # both learn, and bf16 tracks f32 loosely (rounding differs per step)
    assert runs["bf16"][1] > 0.9 and runs["f32"][1] > 0.9
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, atol=0.08),
                 runs["f32"][0], runs["bf16"][0])


def test_top5_metric_reported_for_wide_label_spaces():
    """accTop5 parity with the reference's stored curves: reported when
    class_num > 5, bounded below by top-1."""
    import flax.linen as nn

    class Wide(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            return nn.Dense(20)(x.reshape((x.shape[0], -1)))

    xs, ys = _synthetic_clients(n_clients=4, classes=4)
    ys = [np.minimum(y + 10, 19).astype(np.int32) for y in ys]
    data = _make_fed_data(xs, ys, batch_size=8, classes=20)
    wl = ClassificationWorkload(Wide(), num_classes=20, grad_clip_norm=None)
    algo = FedAvg(wl, data, FedAvgConfig(
        comm_round=3, client_num_per_round=4, epochs=1, batch_size=8,
        lr=0.3, frequency_of_the_test=100))
    p = algo.run(rng=jax.random.key(1))
    stats = algo.evaluate_global(p)
    assert "train_acc_top5" in stats
    assert stats["train_acc_top5"] >= stats["train_acc"]


def test_gspmd_dp_tp_matches_single_chip(workload, devices):
    """dp x tp via GSPMD (tp_shard_params + the plain vmapped step jitted
    over a [clients, model] mesh) must equal the unsharded result — XLA's
    inserted collectives change layout, not math."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from fedml_tpu.parallel.mesh import tp_shard_params

    xs, ys = _synthetic_clients(n_clients=4)
    train = stack_client_data(xs, ys, batch_size=5)
    opt = make_client_optimizer("sgd", 0.1)
    local = make_local_trainer(workload, opt, epochs=1)
    step = make_cohort_step(local)
    params = workload.init(jax.random.key(0),
                           jax.tree.map(lambda v: v[0, 0],
                                        {k: train[k] for k in ("x", "y", "mask")}))
    cohort = {k: jnp.asarray(v) for k, v in train.items()}
    rng = jax.random.key(5)
    want, _ = step(params, cohort, rng)

    mesh = make_mesh(client_axis=4, model_axis=2, devices=devices)
    params_tp = tp_shard_params(params, mesh, min_size=8)
    # the kernel must actually land on the model axis, or this test would
    # green-light a pure-dp run
    assert params_tp["Dense_0"]["kernel"].sharding.spec == P(None, "model")
    cohort_tp = jax.tree.map(
        lambda x: jax.device_put(x, NamedSharding(mesh, P("clients"))),
        cohort)
    got, _ = step(params_tp, cohort_tp, rng)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5), want, got)


def test_tp_shard_params_3d_gate(devices):
    """The Megatron 3-D split only fires on the attention shape signature
    (one strictly-large d_model dim at position 0 or -1): a Conv1D-style
    kernel [k, c_in, c_out] with two comparable large dims must stay
    replicated (round-2 advisor), while real in/out projections shard."""
    from jax.sharding import PartitionSpec as P
    from fedml_tpu.parallel.mesh import tp_shard_params

    mesh = make_mesh(client_axis=4, model_axis=2, devices=devices)
    params = {
        "qkv": jnp.zeros((64, 4, 16)),      # [d_model, H, dh] in-proj
        "out": jnp.zeros((4, 16, 64)),      # [H, dh, d_model] out-proj
        "conv1d": jnp.zeros((3, 32, 32)),   # [k, c_in, c_out]
        "square": jnp.zeros((32, 4, 32)),   # ambiguous: two equal larges
    }
    placed = tp_shard_params(params, mesh, min_size=8)
    assert placed["qkv"].sharding.spec == P(None, "model", None)
    assert placed["out"].sharding.spec == P("model", None, None)
    assert placed["conv1d"].sharding.spec == P()
    assert placed["square"].sharding.spec == P()
