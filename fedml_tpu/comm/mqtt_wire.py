"""MQTT 3.1.1 wire framing — the byte-level subset shared by the in-repo
loopback broker (mqtt_broker.py) and the minimal client (mqtt_client.py).

Reference anchor: the reference's MQTT backend
(fedml_core/distributed/communication/mqtt/mqtt_comm_manager.py:99-120)
delegates framing to paho and runs against a live daemon; neither exists
in this build sandbox, so the frame codec lives here, in ~100 lines of
spec (MQTT 3.1.1, OASIS §2-§3): fixed header = packet type/flags byte +
variable-length Remaining Length (7 bits per byte, MSB = continuation),
UTF-8 strings with 2-byte big-endian length prefixes.

Only the packet types the pub/sub choreography needs are modeled:
CONNECT/CONNACK, PUBLISH (QoS 0/1) + PUBACK, SUBSCRIBE/SUBACK,
UNSUBSCRIBE/UNSUBACK, PINGREQ/PINGRESP, DISCONNECT.
"""

from __future__ import annotations

import socket
import struct
from typing import Optional, Tuple

CONNECT = 1
CONNACK = 2
PUBLISH = 3
PUBACK = 4
SUBSCRIBE = 8
SUBACK = 9
UNSUBSCRIBE = 10
UNSUBACK = 11
PINGREQ = 12
PINGRESP = 13
DISCONNECT = 14


def encode_varint(n: int) -> bytes:
    """Remaining Length encoding (spec §2.2.3): 7 bits per byte, MSB set
    while more bytes follow; max 4 bytes (268 435 455)."""
    if n < 0 or n > 0x0FFFFFFF:
        raise ValueError(f"remaining length {n} out of MQTT range")
    out = bytearray()
    while True:
        n, digit = divmod(n, 128)
        out.append(digit | (0x80 if n else 0))
        if not n:
            return bytes(out)


def encode_string(s: str) -> bytes:
    b = s.encode("utf-8")
    return struct.pack(">H", len(b)) + b


def decode_string(buf: bytes, off: int) -> Tuple[str, int]:
    (n,) = struct.unpack_from(">H", buf, off)
    off += 2
    return buf[off:off + n].decode("utf-8"), off + n


def make_packet(ptype: int, flags: int, body: bytes) -> bytes:
    return bytes([(ptype << 4) | (flags & 0x0F)]) + encode_varint(
        len(body)) + body


def _read_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def read_packet(sock: socket.socket
                ) -> Optional[Tuple[int, int, bytes]]:
    """Blocking read of one full control packet; None on clean EOF."""
    head = _read_exact(sock, 1)
    if head is None:
        return None
    ptype, flags = head[0] >> 4, head[0] & 0x0F
    length, shift = 0, 0
    while True:
        b = _read_exact(sock, 1)
        if b is None:
            return None
        length |= (b[0] & 0x7F) << shift
        if not b[0] & 0x80:
            break
        shift += 7
        if shift > 21:
            raise ValueError("malformed MQTT remaining length")
    body = _read_exact(sock, length) if length else b""
    if body is None:
        return None
    return ptype, flags, body


def topic_matches(filt: str, topic: str) -> bool:
    """MQTT topic-filter matching (spec §4.7): '+' one level, '#' tail."""
    fl, tl = filt.split("/"), topic.split("/")
    for i, f in enumerate(fl):
        if f == "#":
            return True
        if i >= len(tl) or (f != "+" and f != tl[i]):
            return False
    return len(fl) == len(tl)
