"""CV model zoo: shapes, param sanity, norm switch, stateful BN training."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from fedml_tpu.models import (
    resnet56, resnet110, resnet18_gn, vgg11, mobilenet, mobilenet_v3,
    efficientnet)
from fedml_tpu.trainer.workload import ClassificationWorkload
from fedml_tpu.trainer.local_sgd import make_local_trainer


def _n_params(tree):
    return sum(int(np.prod(l.shape)) for l in jax.tree.leaves(tree))


def _fwd(model, shape, train=False):
    x = jnp.zeros(shape, jnp.float32)
    variables = model.init(jax.random.key(0), x)
    rngs = {"dropout": jax.random.key(1)} if train else {}
    if "batch_stats" in variables and train:
        out, _ = model.apply(variables, x, train=True,
                             mutable=["batch_stats"], rngs=rngs)
    else:
        out = model.apply(variables, x, train=train, rngs=rngs)
    return variables, out


@pytest.mark.parametrize("factory,classes,hw", [
    (lambda: resnet56(10), 10, 32),
    (lambda: resnet18_gn(100), 100, 32),
    (lambda: vgg11(10), 10, 32),
    pytest.param(lambda: mobilenet(10), 10, 32, marks=pytest.mark.slow),
    pytest.param(lambda: mobilenet_v3(10, mode="small"), 10, 32,
                 marks=pytest.mark.slow),
    pytest.param(lambda: efficientnet("b0", 10), 10, 32,
                 marks=pytest.mark.slow),
])
def test_forward_shapes(factory, classes, hw):
    model = factory()
    _, out = _fwd(model, (2, hw, hw, 3))
    assert out.shape == (2, classes)


def test_resnet56_depth():
    # Bottleneck [6,6,6]: 18 blocks x 3 convs + stem + fc = 56 layers
    # (resnet.py:202).  Count conv kernels to verify block structure.
    variables, _ = _fwd(resnet56(10), (1, 32, 32, 3))
    convs = [k for k in jax.tree_util.tree_leaves_with_path(variables["params"])
             if k[1].ndim == 4]
    # 55 weight convs = stem 1 + 18*3 + downsample shortcuts (2 stages with
    # projection at entry + the stage-1 expansion shortcut)
    assert len(convs) >= 55


def test_resnet110_deeper_than_56():
    v56, _ = _fwd(resnet56(10), (1, 32, 32, 3))
    v110, _ = _fwd(resnet110(10), (1, 32, 32, 3))
    assert _n_params(v110) > _n_params(v56) * 1.7


def test_batchnorm_variant_has_stats():
    model = resnet56(10, norm="batch")
    variables, _ = _fwd(model, (1, 32, 32, 3))
    assert "batch_stats" in variables
    # group-norm variant must not carry running stats
    vg, _ = _fwd(resnet56(10, norm="group"), (1, 32, 32, 3))
    assert "batch_stats" not in vg


@pytest.mark.slow
def test_stateful_local_training_updates_stats():
    model = resnet56(10, norm="batch")
    wl = ClassificationWorkload(model, 10, stateful=True)
    rng = np.random.RandomState(0)
    data = {
        "x": jnp.asarray(rng.randn(2, 4, 8, 8, 3), jnp.float32),
        "y": jnp.asarray(rng.randint(0, 10, (2, 4)), jnp.int32),
        "mask": jnp.ones((2, 4), jnp.float32),
    }
    sample = jax.tree.map(lambda v: v[0], data)
    params = wl.init(jax.random.key(0), sample)
    train = make_local_trainer(wl, optax.sgd(0.1), epochs=1)
    new_params, _ = jax.jit(train)(params, data, jax.random.key(1))
    # weights moved
    d = jax.tree.map(lambda a, b: float(jnp.abs(a - b).max()),
                     params["params"], new_params["params"])
    assert max(jax.tree.leaves(d)) > 0
    # running stats moved too (spliced from the mutable collection)
    ds = jax.tree.map(lambda a, b: float(jnp.abs(a - b).max()),
                      params["batch_stats"], new_params["batch_stats"])
    assert max(jax.tree.leaves(ds)) > 0


def test_norm_switch_changes_params():
    vb, _ = _fwd(resnet18_gn(10, norm="batch"), (1, 32, 32, 3))
    vg, _ = _fwd(resnet18_gn(10, norm="group"), (1, 32, 32, 3))
    # same trained-param count; batch variant adds running stats
    assert _n_params(vb["params"]) == _n_params(vg["params"])
    assert "batch_stats" in vb and "batch_stats" not in vg


def test_bf16_stateful_batch_stats_stay_f32():
    """Mixed precision with BatchNorm: compute runs bf16 but the running
    stats spliced back into the master tree must come back f32 (they are
    FedAvg-aggregated alongside weights)."""
    import flax.linen as nn
    from fedml_tpu.trainer.local_sgd import make_local_trainer
    from fedml_tpu.trainer.workload import make_client_optimizer

    class TinyBN(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            x = nn.Dense(8)(x.reshape((x.shape[0], -1)))
            x = nn.BatchNorm(use_running_average=not train)(x)
            return nn.Dense(3)(x)

    wl = ClassificationWorkload(TinyBN(), 3, stateful=True,
                                compute_dtype=jnp.bfloat16)
    rng = np.random.RandomState(0)
    data = {"x": jnp.asarray(rng.randn(2, 4, 6), jnp.float32),
            "y": jnp.asarray(rng.randint(0, 3, (2, 4)), jnp.int32),
            "mask": jnp.ones((2, 4), jnp.float32)}
    params = wl.init(jax.random.key(0), jax.tree.map(lambda v: v[0], data))
    local = make_local_trainer(wl, make_client_optimizer("sgd", 0.1), 1)
    p1, _ = local(params, data, jax.random.key(1))
    for leaf in jax.tree.leaves(p1):
        assert leaf.dtype == jnp.float32, leaf.dtype
    # running stats actually moved
    assert not np.allclose(np.asarray(p1["batch_stats"]["BatchNorm_0"]["mean"]),
                           np.asarray(params["batch_stats"]["BatchNorm_0"]["mean"]))
