"""DP-FedAvg with RDP accounting (algorithms/dp_fedavg.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.algorithms import FedAvg, FedAvgConfig
from fedml_tpu.algorithms.dp_fedavg import (DPFedAvg, DPFedAvgConfig,
                                            make_dp_aggregate)
from fedml_tpu.data.stacking import FederatedData, stack_client_data
from fedml_tpu.models import LogisticRegression
from fedml_tpu.trainer.workload import ClassificationWorkload


def _clients(n_clients=4, dim=6, per=24, seed=0):
    rng = np.random.RandomState(seed)
    xs = [rng.randn(per, dim).astype(np.float32) for _ in range(n_clients)]
    ys = [rng.randint(0, 4, per).astype(np.int32) for _ in range(n_clients)]
    return xs, ys


def _fed(xs, ys, batch=8, classes=4):
    train = stack_client_data(xs, ys, batch)
    return FederatedData(client_num=len(xs), class_num=classes,
                         train=train, test=train)


@pytest.fixture(scope="module")
def workload():
    return ClassificationWorkload(LogisticRegression(6, 4), num_classes=4,
                                  grad_clip_norm=None)


def test_no_noise_huge_clip_equals_fedavg_on_equal_shards(workload):
    """z=0 and a clip far above any update norm leaves only the UNIFORM
    mean — which equals FedAvg's sample-weighted mean exactly when every
    client holds the same number of samples."""
    xs, ys = _clients()
    data = _fed(xs, ys)
    cfg = dict(comm_round=2, client_num_per_round=4, epochs=2,
               batch_size=8, lr=0.1, frequency_of_the_test=100)
    fa = FedAvg(workload, data, FedAvgConfig(**cfg))
    dp = DPFedAvg(workload, data, DPFedAvgConfig(
        dp_clip=1e9, dp_noise_multiplier=0.0, **cfg))
    p0 = fa.init_params(jax.random.key(3))
    out_fa = fa.run(params=jax.tree.map(jnp.copy, p0),
                    rng=jax.random.key(4))
    out_dp = dp.run(params=jax.tree.map(jnp.copy, p0),
                    rng=jax.random.key(4))
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, atol=1e-6),
                 out_fa, out_dp)


def test_clip_bounds_the_round_update(workload):
    """With z=0 the server update is a mean of per-client deltas each
    clipped to S, so its global L2 norm is <= S."""
    xs, ys = _clients()
    data = _fed(xs, ys)
    clip = 0.05
    cfg = dict(comm_round=1, client_num_per_round=4, epochs=3,
               batch_size=8, lr=1.0, frequency_of_the_test=100)
    dp = DPFedAvg(workload, data, DPFedAvgConfig(
        dp_clip=clip, dp_noise_multiplier=0.0, **cfg))
    p0 = dp.init_params(jax.random.key(0))
    out = dp.run(params=jax.tree.map(jnp.copy, p0), rng=jax.random.key(1))
    delta_sq = sum(float(jnp.sum(jnp.square(a - b)))
                   for a, b in zip(jax.tree.leaves(out),
                                   jax.tree.leaves(p0)))
    assert np.sqrt(delta_sq) <= clip + 1e-6
    # sanity: the unclipped update would have exceeded the bound
    fa = FedAvg(workload, data, FedAvgConfig(**cfg))
    out_fa = fa.run(params=jax.tree.map(jnp.copy, p0),
                    rng=jax.random.key(1))
    fa_sq = sum(float(jnp.sum(jnp.square(a - b)))
                for a, b in zip(jax.tree.leaves(out_fa),
                                jax.tree.leaves(p0)))
    assert np.sqrt(fa_sq) > clip


def test_noise_is_deterministic_per_seed_and_fresh_per_round(workload):
    xs, ys = _clients()
    data = _fed(xs, ys)
    cfg = dict(comm_round=1, client_num_per_round=4, epochs=1,
               batch_size=8, lr=0.1, frequency_of_the_test=100)

    def run_once(key):
        dp = DPFedAvg(workload, data, DPFedAvgConfig(
            dp_clip=0.5, dp_noise_multiplier=1.0, **cfg))
        p0 = dp.init_params(jax.random.key(9))
        return dp.run(params=jax.tree.map(jnp.copy, p0), rng=key)

    a, b = run_once(jax.random.key(5)), run_once(jax.random.key(5))
    jax.tree.map(lambda x, y: np.testing.assert_array_equal(x, y), a, b)
    c = run_once(jax.random.key(6))
    assert any(not np.array_equal(x, y)
               for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(c)))


def test_aggregate_ignores_padded_slots():
    """Padded (weight-0) cohort slots must not shift the uniform mean."""
    agg = make_dp_aggregate(clip=10.0, noise_multiplier=0.0)
    g = {"w": jnp.zeros((3,))}
    stacked = {"w": jnp.stack([jnp.ones(3), 3 * jnp.ones(3),
                               jnp.full(3, 99.0)])}
    out = agg(stacked, jnp.asarray([4.0, 4.0, 0.0]), g, jax.random.key(0))
    np.testing.assert_allclose(np.asarray(out["w"]),
                               np.full(3, 2.0), atol=1e-6)


def test_epsilon_reported_and_grows(workload):
    xs, ys = _clients()
    data = _fed(xs, ys)
    cfg = dict(comm_round=4, client_num_per_round=2, epochs=1,
               batch_size=8, lr=0.1, frequency_of_the_test=1)
    dp = DPFedAvg(workload, data, DPFedAvgConfig(
        dp_clip=0.5, dp_noise_multiplier=1.0, dp_delta=1e-5, **cfg))
    dp.run(rng=jax.random.key(0))
    eps = [h["dp_epsilon"] for h in dp.history]
    assert all(np.isfinite(e) and e > 0 for e in eps)
    assert all(b > a for a, b in zip(eps, eps[1:]))
    assert dp.history[-1]["dp_delta"] == 1e-5
    # z=0 is honestly non-private
    dp0 = DPFedAvg(workload, data, DPFedAvgConfig(
        dp_clip=0.5, dp_noise_multiplier=0.0,
        **{**cfg, "comm_round": 1}))
    dp0.run(rng=jax.random.key(0))
    assert np.isinf(dp0.history[-1]["dp_epsilon"])


def test_resume_keeps_total_privacy_spent(workload, tmp_path):
    """A kill-and-resume run must report ε for ALL rounds ever run, not
    just the post-resume tail."""
    from fedml_tpu.utils.checkpoint import RoundCheckpointer
    xs, ys = _clients()
    data = _fed(xs, ys)
    cfg = dict(comm_round=4, client_num_per_round=2, epochs=1,
               batch_size=8, lr=0.1, frequency_of_the_test=100)
    full = DPFedAvg(workload, data, DPFedAvgConfig(
        dp_clip=0.5, dp_noise_multiplier=1.0, **cfg))
    full.run(rng=jax.random.key(0))
    eps_full = full.accountant.epsilon()

    half = DPFedAvg(workload, data, DPFedAvgConfig(
        dp_clip=0.5, dp_noise_multiplier=1.0,
        **{**cfg, "comm_round": 2}))
    ck = RoundCheckpointer(str(tmp_path / "ck"), save_every=1)
    half.run(rng=jax.random.key(0), checkpointer=ck)
    resumed = DPFedAvg(workload, data, DPFedAvgConfig(
        dp_clip=0.5, dp_noise_multiplier=1.0, **cfg))
    resumed.run(rng=jax.random.key(0),
                checkpointer=RoundCheckpointer(str(tmp_path / "ck"),
                                               save_every=1))
    assert resumed.accountant.epsilon() == pytest.approx(eps_full)


def test_rejects_bad_configs(workload):
    xs, ys = _clients()
    data = _fed(xs, ys)
    base = dict(comm_round=1, client_num_per_round=2, epochs=1,
                batch_size=8, lr=0.1)
    with pytest.raises(ValueError, match="dp_clip"):
        DPFedAvg(workload, data, DPFedAvgConfig(dp_clip=0.0, **base))
    with pytest.raises(ValueError, match="noise_multiplier"):
        DPFedAvg(workload, data,
                 DPFedAvgConfig(dp_noise_multiplier=-1.0, **base))


@pytest.mark.parametrize("z", [0.0, 1.0])
def test_mesh_sharded_dp_fedavg_equals_single_chip(workload, z):
    """Mesh == single-chip for DP-FedAvg even WITH noise on: the clip is
    per-client (shard-local), the uniform mean psums, and the one
    central draw uses the replicated rng key so every device adds the
    IDENTICAL noise.  Includes a padded cohort (4 live in 8 slots over
    4 devices).  The accountant must actually count mesh rounds (the
    counted_step wrapper wraps the sharded step too)."""
    from fedml_tpu.parallel.mesh import make_mesh
    for n_clients, m, axis in ((4, 4, 4), (4, 8, 4)):
        xs, ys = _clients(n_clients=n_clients)
        data = _fed(xs, ys)
        cfg = dict(dp_clip=0.5, dp_noise_multiplier=z, comm_round=2,
                   client_num_per_round=m, epochs=2, batch_size=8,
                   lr=0.1, frequency_of_the_test=100)
        single = DPFedAvg(workload, data, DPFedAvgConfig(**cfg))
        meshed = DPFedAvg(workload, data, DPFedAvgConfig(**cfg),
                          mesh=make_mesh(client_axis=axis,
                                         devices=jax.devices()[:axis]))
        out_s = single.run(rng=jax.random.key(0))
        out_m = meshed.run(rng=jax.random.key(0))
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-6), out_s, out_m)
        # the mesh path's counted_step must tick the accountant per round
        assert meshed.accountant.steps == cfg["comm_round"]
        if z > 0:
            assert 0 < meshed.accountant.epsilon() < np.inf


def test_cli_dp_fedavg_end_to_end():
    from fedml_tpu.experiments.main import main
    summary = main(["--algo", "dp_fedavg", "--model", "lr", "--dataset",
                    "mnist", "--client_num_in_total", "8",
                    "--client_num_per_round", "4", "--comm_round", "2",
                    "--frequency_of_the_test", "1", "--batch_size", "4",
                    "--dp_noise_multiplier", "1.0", "--dp_clip", "0.5",
                    "--log_stdout", "false"])
    assert np.isfinite(summary["train_loss"])
    assert summary["dp_epsilon"] > 0


def test_cohort_sampling_is_secret_not_the_public_chain(workload):
    """Amplification soundness: with m < N the dp cohorts must come from
    the run rng (secret), NOT the framework's public round-index chain —
    and must be reproducible given the same rng."""
    from fedml_tpu.core.sampling import sample_clients
    xs, ys = _clients(n_clients=8)
    data = _fed(xs, ys)
    cfg = dict(comm_round=6, client_num_per_round=2, epochs=1,
               batch_size=8, lr=0.1, frequency_of_the_test=100)

    def cohorts(key):
        dp = DPFedAvg(workload, data, DPFedAvgConfig(
            dp_clip=0.5, dp_noise_multiplier=1.0, **cfg))
        dp.run(rng=key)
        return [tuple(sorted(dp._sample_round(i).tolist()))
                for i in range(6)]

    a = cohorts(jax.random.key(0))
    assert a == cohorts(jax.random.key(0))  # deterministic per run rng
    assert a != cohorts(jax.random.key(1))  # but rng-dependent (secret)
    public = [tuple(sorted(sample_clients(i, data.client_num, 2).tolist()))
              for i in range(6)]
    assert a != public
    # every cohort is m distinct, in-range clients
    for c in a:
        assert len(c) == 2 and len(set(c)) == 2
        assert all(0 <= i < data.client_num for i in c)


def test_resume_with_different_rng_keeps_secret_cohort_schedule(
        workload, tmp_path):
    """Advisor r4: the secret sampling chain must ride the checkpoint.
    A run resumed with a DIFFERENT rng argument must continue the
    ORIGINAL run's cohort schedule (and therefore reproduce the full
    run's params exactly at z=0), not silently fork it while the
    accountant composes as one run."""
    from fedml_tpu.utils.checkpoint import RoundCheckpointer
    xs, ys = _clients(n_clients=6)
    data = _fed(xs, ys)
    cfg = dict(comm_round=4, client_num_per_round=2, epochs=1,
               batch_size=8, lr=0.1, frequency_of_the_test=100, seed=3)
    mk = lambda rounds: DPFedAvg(workload, data, DPFedAvgConfig(
        dp_clip=100.0, dp_noise_multiplier=0.0,
        **{**cfg, "comm_round": rounds}))

    p_full = mk(4).run(rng=jax.random.key(0))

    half = mk(2)
    half.run(rng=jax.random.key(0),
             checkpointer=RoundCheckpointer(str(tmp_path / "ck"),
                                            save_every=1))
    resumed = mk(4)
    # deliberately different rng on resume: the checkpointed sample_base
    # must win, so cohorts (and thus params at z=0) match the full run
    p_res = resumed.run(rng=jax.random.key(99),
                        checkpointer=RoundCheckpointer(
                            str(tmp_path / "ck"), save_every=1))
    for a, b in zip(jax.tree.leaves(p_full), jax.tree.leaves(p_res)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_accounting_mode_default_exact_and_poisson_option(workload):
    """The default accountant is the fixed-size WOR bound (valid for
    the sampler used); --dp_accounting poisson selects the approximation,
    which reads strictly lower epsilon at the same config."""
    xs, ys = _clients(n_clients=6)
    data = _fed(xs, ys)
    cfg = dict(comm_round=3, client_num_per_round=2, epochs=1,
               batch_size=8, lr=0.1, frequency_of_the_test=100)
    exact = DPFedAvg(workload, data, DPFedAvgConfig(
        dp_noise_multiplier=1.0, **cfg))
    assert exact.accountant.sampling == "fixed_size_wor"
    poisson = DPFedAvg(workload, data, DPFedAvgConfig(
        dp_noise_multiplier=1.0, dp_accounting="poisson", **cfg))
    assert poisson.accountant.sampling == "poisson"
    exact.accountant.step(3)
    poisson.accountant.step(3)
    assert exact.accountant.epsilon() > poisson.accountant.epsilon() > 0
    with pytest.raises(ValueError):
        DPFedAvg(workload, data, DPFedAvgConfig(
            dp_accounting="bogus", **cfg))


def test_resume_from_legacy_checkpoint_without_sample_base(
        workload, tmp_path, monkeypatch):
    """Migration: a pre-round-5 checkpoint (extra = dp_rounds only) must
    still resume — falling back to the rng-derived sampling chain (the
    old behavior), which is correct when the resume passes the ORIGINAL
    run's rng."""
    from fedml_tpu.utils.checkpoint import RoundCheckpointer
    xs, ys = _clients(n_clients=6)
    data = _fed(xs, ys)
    cfg = dict(comm_round=4, client_num_per_round=2, epochs=1,
               batch_size=8, lr=0.1, frequency_of_the_test=100, seed=3)
    mk = lambda rounds: DPFedAvg(workload, data, DPFedAvgConfig(
        dp_clip=100.0, dp_noise_multiplier=0.0,
        **{**cfg, "comm_round": rounds}))

    p_full = mk(4).run(rng=jax.random.key(0))

    # write the checkpoint the OLD code would have written
    monkeypatch.setattr(
        DPFedAvg, "_extra_state",
        lambda self: {"dp_rounds": self.accountant.steps})
    half = mk(2)
    half.run(rng=jax.random.key(0),
             checkpointer=RoundCheckpointer(str(tmp_path / "ck"),
                                            save_every=1))
    monkeypatch.undo()

    resumed = mk(4)
    p_res = resumed.run(rng=jax.random.key(0),
                        checkpointer=RoundCheckpointer(
                            str(tmp_path / "ck"), save_every=1))
    for a, b in zip(jax.tree.leaves(p_full), jax.tree.leaves(p_res)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
