"""Sharded global-model spine (ROADMAP item 2): the round state, wire
path, streaming fold, and defended finalize of the live federation,
laid out per-shard so no device (and no single accumulator) ever holds
the whole model.

* `plan` — the deterministic, checkpoint-verified leaf→shard layout;
* `agg` — the sharded `StreamingAggregator` twin (per-shard folds,
  two-phase clip, fused Pallas finalize);
* `admission` — per-shard structural screens + the combined-norm
  outlier screen, over the shared `TrustTracker`;
* `spine` — the server bundle (`--model_shards`) and the zero-config
  silo assembler.
"""

from fedml_tpu.shard_spine.admission import ShardAdmission
from fedml_tpu.shard_spine.agg import ShardedStreamingAggregator
from fedml_tpu.shard_spine.plan import (ShardPlan, SiloShardCodec,
                                        build_shard_plan)
from fedml_tpu.shard_spine.spine import (ShardSpine, SiloShardAssembler,
                                         build_shard_spine)

__all__ = [
    "ShardAdmission", "ShardedStreamingAggregator", "ShardPlan",
    "ShardSpine", "SiloShardAssembler", "SiloShardCodec",
    "build_shard_plan", "build_shard_spine",
]
